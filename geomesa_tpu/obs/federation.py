"""Fleet-wide observability plane: metrics federation + trace stitching.

PR 7 made the runtime horizontal (router, read replicas, WAL shipping);
this module makes it OBSERVABLE as one system instead of N disconnected
processes — the "refine close to the data, observe far from it" failure
mode the reference avoids by serializing per-layer audit/stat transforms
back to the client (PAPER.md), and Dapper avoids with propagated trace
context (PAPERS.md).

Three pieces:

  Federator   scrapes each fleet node's ``/healthz`` and bucket-exact
              ``/metrics?format=state`` on a TTL, merges counters by
              summation and the fixed-geometry log-bucket histograms
              EXACTLY (every process shares metrics.BUCKET_BOUNDS, so
              summing bucket counts is lossless — fleet percentiles are
              what one process observing everything would report), and
              exposes: ``GET /fleet`` (per-node health/lag/seq/overload),
              ``GET /fleet/metrics`` (Prometheus: per-node counter/gauge
              samples under a ``node`` label, merged histogram families),
              and fleet-level SLO burn rates (the Federator quacks like a
              MetricsRegistry — ``timer_good_total``/``snapshot`` — so the
              UNMODIFIED SloEngine evaluates objectives over merged
              good/total: "count latency" is judged across the fleet).

  stitch()    reassembles ONE cross-process trace tree from per-node
              halves that share a propagated global id (trace.py's
              inject_headers/extract_headers): the remote child's root
              attaches under the parent span that made the hop, with the
              per-hop NETWORK time made explicit (parent span wall time
              minus remote root wall time = wire + serialization).

  collect_trace()  fetches every node's ``GET /traces?id=<gid>`` halves
              (plus this process's rings) for the stitcher — the engine
              behind ``debug trace --fleet`` and the router's
              ``GET /traces?id=``.

Import discipline (obs/__init__ rule): config/metrics/trace/obs.* only —
never planner/scheduler/datastore layers.
"""

from __future__ import annotations

import copy
import json
import threading
import time
import urllib.parse
import urllib.request
from typing import Dict, List, Optional, Tuple

from geomesa_tpu import config
from geomesa_tpu import trace as _trace
from geomesa_tpu.metrics import (BUCKET_BOUNDS, Histogram,
                                 REGISTRY as _metrics, MetricsRegistry,
                                 sanitize_metric_name)


def _label(v: str) -> str:
    """A well-formed prometheus label value (escape per exposition spec)."""
    return str(v).replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


class NodeScrape:
    """One node's latest scrape result."""

    __slots__ = ("name", "ok", "error", "healthz", "state", "ts")

    def __init__(self, name: str):
        self.name = name
        self.ok = False
        self.error: Optional[str] = None
        self.healthz: Optional[dict] = None
        self.state: Optional[dict] = None
        self.ts = 0.0

    @property
    def node_id(self) -> str:
        hz = self.healthz or {}
        node = hz.get("node") or {}
        return str(node.get("id") or self.name)

    @property
    def role(self) -> str:
        hz = self.healthz or {}
        node = hz.get("node") or {}
        role = node.get("role")
        if not role:
            repl = hz.get("replication") or {}
            role = repl.get("role", "standalone")
        return str(role)


def _local_fetch() -> Tuple[dict, dict]:
    """The in-process node's (healthz-lite, state) — the router federates
    its own router.* counters without scraping itself over HTTP."""
    from geomesa_tpu.obs import shardwatch as _shardwatch
    from geomesa_tpu.obs import workload as _workload
    hz = {"status": "ok",
          "node": {"id": _trace.node_id(), "role": _trace.node_role()}}
    from geomesa_tpu.obs import history as _history
    state = _metrics.export_state()
    state["workload"] = _workload.WORKLOAD.export_state()
    state["shardwatch"] = _shardwatch.WATCH.export_state()
    state["history"] = _history.HISTORY.export_state()
    return hz, state


class Federator:
    """TTL-cached scrape + exact merge over a fixed set of fleet nodes.

    ``nodes`` maps node name -> target: a base URL string
    (``http://host:port`` or ``host:port``) scraped over HTTP, or None
    for THIS process (read directly from the local registry)."""

    def __init__(self, nodes: Dict[str, Optional[str]],
                 ttl_ms: Optional[float] = None,
                 timeout_s: Optional[float] = None,
                 clock=time.monotonic):
        self.nodes: Dict[str, Optional[str]] = {}
        for name, target in nodes.items():
            if isinstance(target, str) and target \
                    and not target.startswith("http"):
                target = f"http://{target}"
            self.nodes[name] = target.rstrip("/") if target else None
        self._ttl_ms = ttl_ms
        self._timeout_s = timeout_s
        self._clock = clock
        self._lock = threading.Lock()
        self._scrapes: Dict[str, NodeScrape] = {}
        self._last_refresh = 0.0
        # fleet SLOs ride the unmodified burn-rate engine: the Federator
        # itself implements the registry surface the engine reads
        # (timer_good_total + snapshot()["counters"]) over MERGED state
        from geomesa_tpu.obs import slo as _slo
        self.engine = _slo.SloEngine(registry=self, clock=clock)
        for obj in _slo.default_objectives():
            self.engine.add(obj)
        self.engine.add(_slo.replication_objective())

    # -- scraping -------------------------------------------------------------

    def _timeout(self) -> float:
        return float(self._timeout_s if self._timeout_s is not None
                     else config.FED_TIMEOUT_S.get())

    def _fetch_json(self, base: str, path: str) -> dict:
        with urllib.request.urlopen(base + path,
                                    timeout=self._timeout()) as r:
            return json.loads(r.read().decode())

    def _scrape(self, name: str, target: Optional[str]) -> NodeScrape:
        s = NodeScrape(name)
        s.ts = self._clock()
        try:
            if target is None:
                s.healthz, s.state = _local_fetch()
            else:
                s.healthz = self._fetch_json(target, "/healthz")
                body = self._fetch_json(target, "/metrics?format=state")
                s.state = body.get("state", body)
                # healthz node attribution wins; state meta is the backup
                if "node" not in s.healthz and "node" in body:
                    s.healthz["node"] = body["node"]
            s.ok = True
            _metrics.inc("federation.scrapes")
        except Exception as e:
            s.error = str(e)
            _metrics.inc("federation.scrape_errors")
            # per-node attribution: a flaky node is visible BY NAME, and
            # merged surfaces can mark themselves partial instead of
            # silently presenting N-1 nodes as the fleet
            _metrics.inc(f"fed.scrape_errors.{name}")
        return s

    def refresh(self, force: bool = False) -> Dict[str, NodeScrape]:
        """Scrape every node unless the cached merge is inside the TTL."""
        ttl_s = float(self._ttl_ms if self._ttl_ms is not None
                      else config.FED_TTL_MS.get()) / 1000.0
        now = self._clock()
        with self._lock:
            if not force and self._scrapes \
                    and now - self._last_refresh < ttl_s:
                return dict(self._scrapes)
        scrapes = {name: self._scrape(name, target)
                   for name, target in self.nodes.items()}
        with self._lock:
            self._scrapes = scrapes
            self._last_refresh = now
            return dict(scrapes)

    def _states(self) -> List[NodeScrape]:
        return [s for s in self.refresh().values() if s.ok and s.state]

    def missing_nodes(self) -> List[str]:
        """Names of nodes whose latest scrape failed or timed out — the
        merge over the remaining nodes is PARTIAL, and every merged
        surface says so instead of silently omitting them."""
        return sorted(name for name, s in self.refresh().items()
                      if not (s.ok and s.state))

    # -- exact merge ----------------------------------------------------------

    def merged_counters(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for s in self._states():
            for k, v in (s.state.get("counters") or {}).items():
                out[k] = out.get(k, 0) + int(v)
        return out

    @staticmethod
    def _fold(h: Histogram, hs: dict) -> None:
        h.count += int(hs.get("count", 0))
        h.total_s += float(hs.get("total", 0.0))
        h.max_s = max(h.max_s, float(hs.get("max", 0.0)))
        for i, c in (hs.get("buckets") or {}).items():
            h.buckets[int(i)] += int(c)

    def _merged_hists(self, section: str) \
            -> Dict[str, Tuple[Histogram, Dict[int, tuple]]]:
        """name -> (exactly-merged Histogram, exemplars by bucket). An
        integer exemplar ref from node N rewrites to N's global trace id
        (``<node>-<local id>``) so a fleet reader can fetch it."""
        out: Dict[str, Tuple[Histogram, Dict[int, tuple]]] = {}
        for s in self._states():
            exemplars = s.state.get("exemplars") or {}
            for name, hs in (s.state.get(section) or {}).items():
                if name not in out:
                    out[name] = (Histogram(), {})
                h, ex = out[name]
                self._fold(h, hs)
                if section == "timers":
                    for bi, ref in (exemplars.get(name) or {}).items():
                        tid, sec = ref[0], float(ref[1])
                        if not isinstance(tid, str):
                            tid = f"{s.node_id}-{tid}"
                        ex[int(bi)] = (tid, sec)
        return out

    # -- the registry surface the SLO engine reads ----------------------------

    def timer_good_total(self, name: str, threshold_s: float):
        """Merged (good, total) for one timer across the fleet — the
        fleet-latency-SLO feed (same bucket-resolution semantics as
        MetricsRegistry.timer_good_total, merged losslessly)."""
        good = total = 0
        for s in self._states():
            hs = (s.state.get("timers") or {}).get(name)
            if not hs:
                continue
            total += int(hs.get("count", 0))
            for i, c in (hs.get("buckets") or {}).items():
                if BUCKET_BOUNDS[int(i)] <= threshold_s:
                    good += int(c)
        return good, total

    def snapshot(self) -> dict:
        """Registry-shaped view of the merged fleet (counters merged by
        summation; the availability-SLO feed). Extra keys ride along
        (the SLO engine reads only ``counters``)."""
        missing = self.missing_nodes()
        return {"counters": self.merged_counters(),
                "partial": bool(missing), "missing": missing}

    # -- surfaces -------------------------------------------------------------

    def slo(self) -> dict:
        """Fleet-level burn rates over MERGED good/total samples — 'count
        latency' judged across the fleet, not per node. When the merge is
        partial (a node's scrape failed), burn-PAGE decisions are
        suppressed: a fleet missing a node looks healthier than it is,
        and paging off that view would both mask the real problem and
        train operators to distrust pages. Tickets still stand; each
        suppressed objective says so."""
        res = self.engine.evaluate()
        missing = self.missing_nodes()
        if missing:
            for obj in res.values():
                if not isinstance(obj, dict):
                    continue
                if obj.get("page"):
                    obj["page"] = False
                    obj["page_suppressed"] = True
                    obj["status"] = "ticket" if obj.get("ticket") else "ok"
        return res

    def fleet(self) -> dict:
        """The single pane of glass: per-node health, role, replication
        lag, wal/synced seq, overload (admission/breaker/queue), fenced
        and draining state — plus the fleet SLO rollup."""
        nodes = {}
        for name, s in sorted(self.refresh().items()):
            if not s.ok:
                nodes[name] = {"ok": False, "error": s.error}
                continue
            hz = s.healthz or {}
            repl = hz.get("replication") or {}
            over = hz.get("overload") or {}
            dur = hz.get("durability") or {}
            nodes[name] = {
                "ok": True,
                "node_id": s.node_id,
                "role": s.role,
                "status": hz.get("status"),
                "fenced": bool(repl.get("fenced")),
                "lag_ms": repl.get("lag_ms"),
                "lag_seqs": repl.get("lag_seqs"),
                "applied_seq": repl.get("applied_seq",
                                        repl.get("last_seq")),
                "epoch": repl.get("epoch"),
                "wal_seq": dur.get("wal_seq"),
                "synced_seq": dur.get("synced_seq"),
                "scheduler": over.get("scheduler"),
                "queue_depth": over.get("queue_depth"),
                "admission": over.get("admission"),
                "breaker": (over.get("breaker") or {}).get("state"),
                "draining": bool((over.get("admission") or {})
                                 .get("draining")),
                "slo": (hz.get("slo") or {}).get("status"),
            }
        missing = self.missing_nodes()
        return {"nodes": nodes,
                "slo": self.slo(),
                "partial": bool(missing), "missing": missing,
                "repl_e2e_ms": self._repl_e2e_summary()}

    def fleet_workload(self) -> dict:
        """Fleet-wide workload intelligence: every node's windowed
        rollup/sketch state (riding the same /metrics?format=state
        scrape) merged exactly — aligned windows sum bucket counts,
        SpaceSaving sketches merge with propagated error bounds — then
        summarized through the SAME read surfaces a single node exposes,
        so /workload and /fleet/workload speak one schema."""
        from geomesa_tpu.obs import workload as _workload
        states, nodes = [], {}
        for name, s in sorted(self.refresh().items()):
            if not (s.ok and s.state):
                nodes[name] = {"ok": False, "error": s.error}
                continue
            wst = s.state.get("workload") or {}
            states.append(wst)
            nodes[name] = {"ok": True, "node_id": s.node_id,
                           "consumed": int(wst.get("consumed", 0)),
                           "dropped": int(wst.get("dropped", 0))}
        merged = _workload.WorkloadAnalytics.from_state(
            _workload.merge_states(states))
        missing = self.missing_nodes()
        return {"nodes": nodes,
                "partial": bool(missing), "missing": missing,
                "hot_set": merged.hot_set(),
                "tenants": merged.top_tenants(),
                "rollups": merged.rollups()}

    def fleet_history(self) -> dict:
        """Fleet timelines: every node's retained history rings (riding
        the same /metrics?format=state scrape) merged per equal tier —
        counter rates and gauges sum at aligned slots, timer slots sum
        bucket counts losslessly — with honest per-node gap markers: a
        node whose scrape is pinned or whose sampler skipped a tick is
        NAMED in the slots it misses instead of silently deflating the
        fleet sum (see history.merge_states)."""
        from geomesa_tpu.obs import history as _history
        states, names, nodes = [], [], {}
        for name, s in sorted(self.refresh().items()):
            if not (s.ok and s.state):
                nodes[name] = {"ok": False, "error": s.error}
                continue
            hst = s.state.get("history") or {}
            states.append(hst)
            names.append(name)
            n_series = len({sn for t in hst.get("tiers", [])
                            for sn in (t.get("series") or {})})
            nodes[name] = {"ok": True, "node_id": s.node_id,
                           "series": n_series}
        merged = _history.merge_states(states, node_names=names)
        missing = self.missing_nodes()
        return {"nodes": nodes,
                "partial": bool(missing), "missing": missing,
                "merged": merged}

    def fleet_balance(self) -> dict:
        """Fleet-wide shard balance: every node's shardwatch + workload
        state (riding the same /metrics?format=state scrape) merged —
        per-cell cost stats sum, hot-cell sketches merge with propagated
        error bounds, the rank-identical shard maps union — then joined
        through the SAME ledger a single node runs, so /cluster/balance
        and /fleet/balance speak one schema."""
        from geomesa_tpu.obs import shardwatch as _shardwatch
        from geomesa_tpu.obs import workload as _workload
        wl_states, sw_states, nodes = [], [], {}
        for name, s in sorted(self.refresh().items()):
            if not (s.ok and s.state):
                nodes[name] = {"ok": False, "error": s.error}
                continue
            swst = s.state.get("shardwatch") or {}
            wl_states.append(s.state.get("workload") or {})
            sw_states.append(swst)
            nodes[name] = {"ok": True, "node_id": s.node_id,
                           "types": sorted((swst.get("maps")
                                            or {}).keys()),
                           "cells_tracked": len(swst.get("cells") or ())}
        report = _shardwatch.fleet_balance_report(
            _workload.merge_states(wl_states), sw_states)
        missing = self.missing_nodes()
        return {"nodes": nodes,
                "partial": bool(missing), "missing": missing,
                "balance": report}

    def fleet_incidents(self) -> dict:
        """Every node's doctor incidents under one pane with node
        attribution — the ``GET /fleet/incidents`` payload. The local
        process (target None) reads its DOCTOR directly; remote nodes
        serve ``GET /incidents``. Unreachable nodes mark the answer
        partial rather than vanishing."""
        nodes: Dict[str, dict] = {}
        incidents: List[dict] = []
        missing: List[str] = []
        for name, target in sorted(self.nodes.items()):
            try:
                if target is None:
                    from geomesa_tpu.obs.doctor import DOCTOR
                    body = DOCTOR.incidents()
                else:
                    body = self._fetch_json(target, "/incidents")
            except Exception as e:
                nodes[name] = {"ok": False, "error": str(e)}
                missing.append(name)
                _metrics.inc(f"fed.scrape_errors.{name}")
                continue
            node_incidents = body.get("incidents") or []
            nodes[name] = {"ok": True,
                           "active": sum(1 for i in node_incidents
                                         if i.get("status") == "open"),
                           "total": len(node_incidents)}
            for inc in node_incidents:
                inc = dict(inc)
                inc["fleet_node"] = name
                incidents.append(inc)
        incidents.sort(key=lambda i: i.get("opened_ms", 0))
        return {"nodes": nodes, "incidents": incidents,
                "partial": bool(missing), "missing": sorted(missing)}

    def _repl_e2e_summary(self) -> Optional[dict]:
        merged = self._merged_hists("timers")
        pair = merged.get("repl.e2e")
        if pair is None or pair[0].count == 0:
            return None
        h, ex = pair
        out = h.to_dict()
        out["exemplars"] = {str(BUCKET_BOUNDS[bi]): tid
                            for bi, (tid, _sec) in sorted(ex.items())}
        return out

    def to_prometheus(self) -> str:
        """Federated exposition: counter/gauge families carry one sample
        PER NODE under a ``node`` label; timer/value histograms are
        merged fleet-wide (summary quantiles + native cumulative
        ``_bucket`` lines, exemplars rewritten to fetchable global trace
        ids). One # TYPE line per family across all nodes."""
        scrapes = [s for s in self.refresh().values() if s.ok and s.state]
        lines: List[str] = []
        # partiality is a first-class sample: scrapers see WHICH nodes
        # the merge below is missing, not just that some scrape failed
        missing = self.missing_nodes()
        lines.append("# TYPE geomesa_tpu_fed_scrape_missing gauge")
        lines.append(f"geomesa_tpu_fed_scrape_missing {len(missing)}")
        for name in missing:
            lines.append('geomesa_tpu_fed_scrape_missing'
                         f'{{node="{_label(name)}"}} 1')
        # counters: one family, one labeled sample per node
        families: Dict[str, List[tuple]] = {}
        for s in scrapes:
            for name, v in (s.state.get("counters") or {}).items():
                families.setdefault(name, []).append((s.node_id, v))
        for name in sorted(families):
            m = sanitize_metric_name(name) + "_total"
            lines.append(f"# TYPE {m} counter")
            for nid, v in sorted(families[name]):
                lines.append(f'{m}{{node="{_label(nid)}"}} {v}')
        # gauges: same, honoring the monotone *_total-exports-as-counter
        # contract the per-process exposition applies
        families = {}
        for s in scrapes:
            for name, v in (s.state.get("gauges") or {}).items():
                try:
                    families.setdefault(name, []).append((s.node_id,
                                                          float(v)))
                except (TypeError, ValueError):
                    continue
        for name in sorted(families):
            m = sanitize_metric_name(name)
            kind = "counter" if name.endswith("_total") else "gauge"
            lines.append(f"# TYPE {m} {kind}")
            for nid, v in sorted(families[name]):
                lines.append(f'{m}{{node="{_label(nid)}"}} {v:g}')
        # histograms: merged exactly (same buckets on every node)
        for section, suffix in (("timers", "_seconds"), ("values", "")):
            merged = self._merged_hists(section)
            for name in sorted(merged):
                h, ex = merged[name]
                m = sanitize_metric_name(name) + suffix
                summ = h.to_dict()
                lines.append(f"# TYPE {m} summary")
                if h.count:
                    for q, key in ((0.5, "p50_ms"), (0.9, "p90_ms"),
                                   (0.99, "p99_ms")):
                        lines.append(f'{m}{{quantile="{q}"}} '
                                     f'{summ[key] / 1000:.9g}')
                lines.append(f"{m}_count {h.count}")
                lines.append(f"{m}_sum {h.total_s:.9g}")
                mh = m + "_hist"
                lines.append(f"# TYPE {mh} histogram")
                MetricsRegistry._bucket_lines(lines, mh, h.buckets,
                                              h.count, h.total_s,
                                              ex or None)
        return "\n".join(lines) + "\n"


# -- trace stitching ----------------------------------------------------------


def _index_spans(span: dict, node: Optional[str],
                 index: Dict[int, tuple]) -> None:
    sid = span.get("span_id")
    if sid is not None:
        index[int(sid)] = (span, node)
    for c in span.get("children") or ():
        _index_spans(c, node, index)


def stitch(traces: List[dict]) -> Optional[dict]:
    """Assemble ONE cross-process tree from per-node trace halves sharing
    a global id. The half with no remote parent is the root; every other
    half attaches under the span its ``parent.span`` names, wrapped in a
    synthetic ``remote`` span whose ``network_ms`` makes the hop cost
    explicit (parent span wall time minus remote root wall time)."""
    if not traces:
        return None
    roots = [t for t in traces if not t.get("parent")]
    root = roots[0] if roots else min(
        traces, key=lambda t: t.get("ts_ms", 0))
    tree = copy.deepcopy(root.get("root") or {})
    tree["node"] = root.get("node")
    tree["role"] = root.get("role")
    index: Dict[int, tuple] = {}
    _index_spans(tree, root.get("node"), index)
    hops = []
    rest = sorted((t for t in traces if t is not root),
                  key=lambda t: t.get("ts_ms", 0))
    for ch in rest:
        parent = (ch.get("parent") or {})
        pspan, pnode = index.get(int(parent.get("span") or 0),
                                 (None, None))
        child_tree = copy.deepcopy(ch.get("root") or {})
        child_tree["node"] = ch.get("node")
        child_tree["role"] = ch.get("role")
        net = None
        if pspan is not None:
            net = round(max(0.0, float(pspan.get("duration_ms", 0.0))
                            - float(ch.get("duration_ms", 0.0))), 3)
        remote = {"name": f"remote:{ch.get('node')}", "kind": "remote",
                  "node": ch.get("node"), "role": ch.get("role"),
                  "duration_ms": ch.get("duration_ms"),
                  "network_ms": net,
                  "children": [child_tree]}
        target = pspan if pspan is not None else tree
        target.setdefault("children", []).append(remote)
        _index_spans(child_tree, ch.get("node"), index)
        hops.append({"from": pnode or root.get("node"),
                     "to": ch.get("node"), "network_ms": net,
                     "remote_ms": ch.get("duration_ms")})
    return {"global_id": root.get("global_id"), "name": root.get("name"),
            "duration_ms": root.get("duration_ms"),
            "nodes": [root.get("node")] + [t.get("node") for t in rest],
            "hops": hops, "spans": tree}


def render_stitched(st: Optional[dict]) -> str:
    """ASCII tree of a stitched trace — ``debug trace --fleet`` output."""
    if st is None:
        return "(no trace halves found)"
    lines = [f"trace {st.get('global_id')} [{st.get('name')}] "
             f"{st.get('duration_ms')}ms across {st.get('nodes')}"]

    def walk(span: dict, depth: int) -> None:
        pad = "  " * depth
        extra = ""
        if span.get("kind") == "remote":
            extra = (f"  node={span.get('node')}"
                     f" network={span.get('network_ms')}ms")
        elif span.get("node"):
            extra = f"  node={span.get('node')} ({span.get('role')})"
        lines.append(f"{pad}{span.get('name')} "
                     f"[{span.get('kind')}] {span.get('duration_ms')}ms"
                     f"{extra}")
        for c in span.get("children") or ():
            walk(c, depth + 1)

    walk(st.get("spans") or {}, 1)
    return "\n".join(lines)


def local_traces_by_id(gid: str) -> List[dict]:
    """This process's halves of a (global or local) trace id, searched
    across the recent ring AND the tail-sampled ring."""
    from geomesa_tpu.obs.sampling import SAMPLER
    gid = str(gid)
    seen, out = set(), []
    for t in _trace.RING.recent(None) + SAMPLER.recent(None):
        if t.get("global_id") == gid or str(t.get("id")) == gid:
            key = (t.get("node"), t.get("id"))
            if key not in seen:
                seen.add(key)
                out.append(t)
    return out


def fetch_traces(base_url: str, gid: str,
                 timeout_s: Optional[float] = None) -> List[dict]:
    """One node's halves of a global trace via ``GET /traces?id=``."""
    base = base_url if base_url.startswith("http") \
        else f"http://{base_url}"
    url = f"{base.rstrip('/')}/traces?id={urllib.parse.quote(str(gid))}"
    t = float(timeout_s if timeout_s is not None
              else config.FED_TIMEOUT_S.get())
    with urllib.request.urlopen(url, timeout=t) as r:
        return json.loads(r.read().decode()).get("traces", [])


def collect_trace(gid: str, nodes: Dict[str, Optional[str]]) -> List[dict]:
    """Every reachable node's halves of ``gid`` (local process included
    for None targets), deduplicated by (node, local id)."""
    seen, out = set(), []
    for name, target in nodes.items():
        try:
            halves = local_traces_by_id(gid) if target is None \
                else fetch_traces(target, gid)
        except Exception:
            _metrics.inc("federation.trace_fetch_errors")
            continue
        for t in halves:
            key = (t.get("node"), t.get("id"))
            if key not in seen:
                seen.add(key)
                out.append(t)
    return out


# -- process-global federator (the /fleet surface's backing) ------------------

FEDERATOR: Optional[Federator] = None


def configure(nodes: Dict[str, Optional[str]],
              ttl_ms: Optional[float] = None) -> Federator:
    """Install the process-global federator backing ``GET /fleet`` /
    ``GET /fleet/metrics`` on this node's web surface (the router/primary
    is the natural host; any node can federate)."""
    global FEDERATOR
    FEDERATOR = Federator(nodes, ttl_ms=ttl_ms)
    return FEDERATOR


def federator() -> Optional[Federator]:
    return FEDERATOR
