"""Metrics/observability: counters, histogram timers, gauges, reporters.

≙ the reference's converter ingest metrics + audit surface (SURVEY.md §5:
dropwizard metrics with graphite/cloudwatch/ganglia reporters in
geomesa-convert-metrics-*; QueryEvent audit records in index/audit/
QueryEvent.scala:13). Here a process-local registry collects ingest and
query counters/timers; ``snapshot()`` serializes for the CLI/REST surface,
``to_prometheus()`` emits the text exposition format, and ``add_reporter``
hooks a callable for external sinks (the graphite-reporter slot).

Timers are fixed-bucket log-scale histograms (dropwizard's reservoir slot):
bucket upper bounds grow geometrically by 2^0.25 from 1µs, so percentiles
carry ≤ ~19% relative error at O(bytes) cost and zero allocation per
observation. ``percentile()`` returns the UPPER BOUND of the bucket holding
the rank-th observation (deterministic, never an interpolated value that no
observation produced).

Reset semantics (the snapshot/reset race): ``reset()`` bumps a generation
counter; a ``time()`` block that STRADDLES a reset is discarded at exit
rather than resurrecting its name with a lost count — post-reset snapshots
only ever contain observations that started after the reset.
"""

from __future__ import annotations

import bisect
import math
import os
import threading
import time
from collections import defaultdict
from contextlib import contextmanager
from typing import Callable, Dict, List, Optional

# -- histogram geometry ------------------------------------------------------

_BUCKET_MIN_S = 1e-6          # first bucket: everything <= 1µs
_BUCKET_FACTOR = 2.0 ** 0.25  # ~19% resolution per bucket
_N_BUCKETS = 128              # reaches 1e-6 * 2^(127/4) ≈ 3.3e3 s

# upper (inclusive) bound of each bucket; the last is +inf-in-spirit
BUCKET_BOUNDS: tuple = tuple(
    _BUCKET_MIN_S * _BUCKET_FACTOR ** i for i in range(_N_BUCKETS))


def bucket_index(seconds: float) -> int:
    """First bucket whose upper bound >= seconds (exact via bisect — no
    float-log boundary jitter)."""
    i = bisect.bisect_left(BUCKET_BOUNDS, seconds)
    return min(i, _N_BUCKETS - 1)


def sanitize_metric_name(name: str) -> str:
    """Dotted registry name -> prometheus metric name (shared by the
    process exposition and the federated fleet exposition, so the same
    series keeps the same name in both)."""
    return "geomesa_tpu_" + "".join(
        c if c.isalnum() or c == "_" else "_" for c in name)


class Histogram:
    """Log-scale fixed-bucket duration histogram (count/total/max +
    percentiles). Not internally locked — the registry lock covers it."""

    __slots__ = ("count", "total_s", "max_s", "buckets")

    def __init__(self):
        self.count = 0
        self.total_s = 0.0
        self.max_s = 0.0
        self.buckets = [0] * _N_BUCKETS

    def observe(self, seconds: float) -> None:
        self.count += 1
        self.total_s += seconds
        if seconds > self.max_s:
            self.max_s = seconds
        self.buckets[bucket_index(seconds)] += 1

    def percentile(self, q: float) -> float:
        """Upper bound (seconds) of the bucket holding the ceil(q*count)-th
        observation; 0.0 on an empty histogram."""
        if self.count == 0:
            return 0.0
        rank = max(1, math.ceil(q * self.count))
        cum = 0
        for i, c in enumerate(self.buckets):
            cum += c
            if cum >= rank:
                return BUCKET_BOUNDS[i]
        return BUCKET_BOUNDS[-1]

    def to_dict(self) -> dict:
        return {
            "count": self.count,
            "total_s": round(self.total_s, 6),
            "mean_ms": round(self.total_s / self.count * 1000, 3)
            if self.count else 0.0,
            "max_ms": round(self.max_s * 1000, 3),
            "p50_ms": round(self.percentile(0.50) * 1000, 3),
            "p90_ms": round(self.percentile(0.90) * 1000, 3),
            "p99_ms": round(self.percentile(0.99) * 1000, 3),
        }

    def to_value_dict(self) -> dict:
        """Raw-unit summary for value histograms (batch sizes, queue depths —
        anything that isn't a duration; no ms conversion)."""
        return {
            "count": self.count,
            "total": round(self.total_s, 6),
            "mean": round(self.total_s / self.count, 3) if self.count else 0.0,
            "max": round(self.max_s, 3),
            "p50": round(self.percentile(0.50), 3),
            "p90": round(self.percentile(0.90), 3),
            "p99": round(self.percentile(0.99), 3),
        }


class MetricsRegistry:
    """Thread-safe counters + histogram timers + gauges."""

    def __init__(self):
        self._lock = threading.Lock()
        self._gen = 0
        self._counters: Dict[str, int] = defaultdict(int)
        self._timers: Dict[str, Histogram] = defaultdict(Histogram)
        # value histograms: same log-bucket geometry, raw units (batch
        # sizes, flush waits in queries, …) — the scheduler's distribution
        # surface. Buckets start at 1e-6 so any positive value lands exactly.
        self._values: Dict[str, Histogram] = defaultdict(Histogram)
        self._gauges: Dict[str, object] = {}  # value or zero-arg callable
        self._reporters: List[Callable[[str, str, float], None]] = []
        # span trees awaiting histogram feed (GIL-atomic appends from trace
        # close; drained under the lock at snapshot time) — keeps the
        # per-query trace-close cost to one list append. Entries are
        # (root, trace_id) so retained traces can land bucket exemplars.
        self._pending: List[object] = []
        # timer name -> {bucket index -> (trace_id, seconds)}: the newest
        # RETAINED trace that observed into that bucket (OpenMetrics
        # exemplar slot). Populated at drain time through _exemplar_filter
        # (obs/sampling installs it — only tail-retained traces qualify,
        # so every exemplar links to a trace a reader can actually fetch).
        self._exemplars: Dict[str, Dict[int, tuple]] = {}
        self._exemplar_filter: Optional[Callable[[int], bool]] = None
        # runs BEFORE the lock on every snapshot-ish read: obs/sampling
        # drains its deferred retention queue here, so the exemplar filter
        # (consulted under the lock) sees up-to-date retention without ever
        # nesting locks
        self._pre_drain_hook: Optional[Callable[[], None]] = None

    def inc(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counters[name] += n
            reporters = list(self._reporters)
        self._report(reporters, "counter", name, n)

    def observe(self, name: str, seconds: float) -> None:
        """Record one duration into the name's histogram (the span feed —
        the µs-scale hot path; skip the reporter copy when there are none)."""
        with self._lock:
            self._timers[name].observe(seconds)
            reporters = list(self._reporters) if self._reporters else None
        if reporters:
            self._report(reporters, "timer", name, seconds)

    def observe_batch(self, pairs) -> None:
        """Record many (name, seconds) at once under ONE lock acquisition."""
        with self._lock:
            for name, seconds in pairs:
                self._timers[name].observe(seconds)
            reporters = list(self._reporters) if self._reporters else None
        if reporters:
            for name, seconds in pairs:
                self._report(reporters, "timer", name, seconds)

    def observe_value(self, name: str, value: float) -> None:
        """Record one raw-unit observation (NOT a duration) into the name's
        value histogram — batch sizes, cover cardinalities, queue depths."""
        with self._lock:
            self._values[name].observe(value)

    def observe_exemplar(self, name: str, seconds: float,
                         trace_ref: str) -> None:
        """Record one duration AND pin ``trace_ref`` as the bucket's
        exemplar. Unlike drain-time exemplars (integer local trace ids
        re-checked against tail retention), a PINNED exemplar is a string
        reference to a trace on another node (e.g. a follower's apply
        trace riding a replication ack) — the local retention filter
        cannot vouch for it, so it is kept as-is until overwritten."""
        with self._lock:
            self._timers[name].observe(seconds)
            self._exemplars.setdefault(name, {})[
                bucket_index(seconds)] = (str(trace_ref), seconds)

    def feed_tree(self, root, trace_id: Optional[int] = None) -> None:
        """Defer a whole span tree (an object with ``walk()`` yielding nodes
        with ``name``/``duration_ms``) to the next drain — the trace-close
        hot-path feed: ONE locked list append now, histogram math at
        snapshot time. Reporters consequently see trace-span timer events at
        drain time (they poll snapshots anyway, the dropwizard model).
        ``trace_id`` tags the tree so retained traces become exemplars.
        Lockless by design (list appends are GIL-atomic; the drain swap
        under the lock captures the same list object, so nothing is
        lost) — this is the trace-close hot path."""
        self._pending.append((root, trace_id))

    def set_exemplar_filter(self, fn: Optional[Callable[[int], bool]]) -> None:
        """``fn(trace_id) -> bool`` gates which drained trees land bucket
        exemplars (obs/sampling installs its retained-set membership).
        MUST NOT acquire this registry's lock."""
        with self._lock:
            self._exemplar_filter = fn

    def set_pre_drain_hook(self, fn: Optional[Callable[[], None]]) -> None:
        """Zero-arg hook run before snapshot/export/timer_good_total take
        the lock (the tail sampler's deferred-decision drain slot)."""
        self._pre_drain_hook = fn

    def _pre_drain(self) -> None:
        hook = self._pre_drain_hook
        if hook is not None:
            try:
                hook()
            except Exception:
                pass  # a failing drain must never fail the surface

    def _drain_locked(self) -> Optional[list]:
        """Fold pending span trees into the histograms (lock held). Returns
        (name, seconds) pairs for the reporter fan-out, or None."""
        if not self._pending:
            return None
        pending, self._pending = self._pending, []
        flt = self._exemplar_filter
        pairs = []
        for root, tid in pending:
            keep = False
            if tid is not None and flt is not None:
                try:
                    keep = bool(flt(tid))
                except Exception:
                    keep = False
            for s in root.walk():
                seconds = s.duration_ms / 1000.0
                pairs.append((s.name, seconds))
                if keep:
                    self._exemplars.setdefault(s.name, {})[
                        bucket_index(seconds)] = (tid, seconds)
        for name, seconds in pairs:
            self._timers[name].observe(seconds)
        return pairs if self._reporters else None

    def timer_good_total(self, name: str, threshold_s: float):
        """(good, total) observation counts for one timer, where 'good'
        means the observation landed in a bucket whose UPPER bound is
        <= threshold_s (conservative by at most one bucket factor, ~19%).
        The SLO engine's latency feed. Drains pending trees first so the
        answer reflects every closed trace."""
        self._pre_drain()
        with self._lock:
            self._drain_locked()
            h = self._timers.get(name)
            if h is None or h.count == 0:
                return 0, 0
            good = 0
            for i, c in enumerate(h.buckets):
                if BUCKET_BOUNDS[i] > threshold_s:
                    break
                good += c
            return good, h.count

    @contextmanager
    def time(self, name: str):
        t0 = time.perf_counter()
        gen = self._gen  # racy read is fine: reset() bumps under the lock,
        # and the exit-side compare re-reads under the lock
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            reporters = None
            with self._lock:
                if self._gen == gen:
                    self._timers[name].observe(dt)
                    reporters = list(self._reporters)
                # else: straddled a reset() — discard, never resurrect
            if reporters is not None:
                self._report(reporters, "timer", name, dt)

    def set_gauge(self, name: str, value) -> None:
        """Set a gauge to a value OR a zero-arg callable evaluated lazily at
        snapshot time (resident rows, device memory, …)."""
        with self._lock:
            self._gauges[name] = value

    @staticmethod
    def _report(reporters, kind: str, name: str, value: float) -> None:
        for r in reporters:
            try:
                r(kind, name, value)
            except Exception:
                pass  # a failing sink must never fail the store (dropwizard rule)

    def add_reporter(self, fn: Callable[[str, str, float], None]) -> None:
        """fn(kind, name, value) — the external-sink slot (graphite/etc.)."""
        with self._lock:
            self._reporters.append(fn)

    def _gauge_values(self) -> Dict[str, float]:
        with self._lock:
            items = list(self._gauges.items())
        out = {}
        for k, v in items:
            if callable(v):
                try:
                    v = v()
                except Exception:
                    continue  # a failing probe must never fail the surface
            if v is not None:
                out[k] = v
        return out

    def snapshot(self) -> dict:
        self._pre_drain()
        gauges = self._gauge_values()  # probes run OUTSIDE the lock
        with self._lock:
            pairs = self._drain_locked()
            reporters = list(self._reporters) if pairs else None
            out = {
                "counters": dict(self._counters),
                "timers": {k: h.to_dict() for k, h in self._timers.items()},
                "histograms": {k: h.to_value_dict()
                               for k, h in self._values.items()},
                "gauges": gauges,
            }
        if pairs:
            for name, seconds in pairs:
                self._report(reporters, "timer", name, seconds)
        return out

    def snapshot_prefixed(self, *prefixes: str) -> dict:
        """``snapshot()`` filtered to names under the given prefixes — the
        focused debug surfaces (CLI ``debug admission``/``debug scheduler``,
        web overload state) without the whole registry."""
        snap = self.snapshot()
        return {section: {k: v for k, v in values.items()
                          if k.startswith(prefixes)}
                for section, values in snap.items()}

    def export_state(self) -> dict:
        """Bucket-exact registry state for metrics federation (the
        ``/metrics?format=state`` payload): counters, gauge values, and
        every timer/value histogram as (count, total, max, sparse
        buckets). Every process shares ONE fixed log-bucket geometry
        (BUCKET_BOUNDS), so a federator can merge histograms across
        nodes LOSSLESSLY by summing bucket counts — fleet percentiles
        are exactly what one process observing everything would report."""
        self._pre_drain()
        gauges = self._gauge_values()

        def hist_state(h: Histogram) -> dict:
            return {"count": h.count, "total": h.total_s, "max": h.max_s,
                    "buckets": {str(i): c for i, c in enumerate(h.buckets)
                                if c}}

        with self._lock:
            pairs = self._drain_locked()
            reporters = list(self._reporters) if pairs else None
            flt = self._exemplar_filter
            exemplars = {}
            for name, by_bucket in self._exemplars.items():
                kept = {}
                for bi, (tid, sec) in by_bucket.items():
                    try:
                        if isinstance(tid, str) or flt is None or flt(tid):
                            kept[str(bi)] = [tid, sec]
                    except Exception:
                        pass
                if kept:
                    exemplars[name] = kept
            out = {"bucket_geometry": [_N_BUCKETS, _BUCKET_MIN_S,
                                       _BUCKET_FACTOR],
                   "counters": dict(self._counters),
                   "gauges": gauges,
                   "timers": {k: hist_state(h)
                              for k, h in self._timers.items()},
                   "values": {k: hist_state(h)
                              for k, h in self._values.items()},
                   "exemplars": exemplars}
        if pairs:
            for name, seconds in pairs:
                self._report(reporters, "timer", name, seconds)
        return out

    def _export_locked_state(self):
        """One consistent view for the exposition: (counters, timer
        summaries+buckets, value summaries+buckets, exemplars) captured
        under ONE lock hold, so the summary and histogram families of a
        metric can never disagree. Gauges probe outside the lock."""
        self._pre_drain()
        gauges = self._gauge_values()
        with self._lock:
            pairs = self._drain_locked()
            reporters = list(self._reporters) if pairs else None
            counters = dict(self._counters)
            timers = {k: (h.to_dict(), list(h.buckets), h.total_s)
                      for k, h in self._timers.items()}
            values = {k: (h.to_value_dict(), list(h.buckets), h.total_s)
                      for k, h in self._values.items()}
            flt = self._exemplar_filter
            exemplars = {}
            for name, by_bucket in self._exemplars.items():
                kept = {}
                for bi, (tid, sec) in by_bucket.items():
                    # re-check retention at emission: a trace evicted from
                    # the tail-sampled ring must not leave a dangling link.
                    # String refs are PINNED cross-node exemplars
                    # (observe_exemplar) the local filter cannot judge.
                    try:
                        if isinstance(tid, str) or flt is None or flt(tid):
                            kept[bi] = (tid, sec)
                    except Exception:
                        pass
                by_bucket.clear()
                by_bucket.update(kept)
                if kept:
                    exemplars[name] = dict(kept)
        if pairs:
            for name, seconds in pairs:
                self._report(reporters, "timer", name, seconds)
        return counters, gauges, timers, values, exemplars

    @staticmethod
    def _bucket_lines(lines: List[str], m: str, buckets: List[int],
                      count: int, total: float,
                      exemplars: Optional[Dict[int, tuple]]) -> None:
        """Native cumulative ``_bucket{le=...}`` lines (only bounds that
        hold observations — le stays strictly increasing, cumulative counts
        non-decreasing) + the +Inf bucket, _count and _sum. Buckets backed
        by a retained trace carry an OpenMetrics-style exemplar."""
        cum = 0
        for i, c in enumerate(buckets):
            if not c:
                continue
            cum += c
            line = f'{m}_bucket{{le="{BUCKET_BOUNDS[i]:.9g}"}} {cum}'
            ex = exemplars.get(i) if exemplars else None
            if ex is not None:
                line += f' # {{trace_id="{ex[0]}"}} {ex[1]:.9g}'
            lines.append(line)
        lines.append(f'{m}_bucket{{le="+Inf"}} {count}')
        lines.append(f"{m}_count {count}")
        lines.append(f"{m}_sum {total:.9g}")

    def to_prometheus(self) -> str:
        """Prometheus text exposition: counters as *_total, gauges as
        gauges, and each timer/value histogram as TWO families — the
        ``summary`` family (p50/p90/p99 quantile lines, the established
        names) plus a native ``histogram`` family under ``<name>_hist``
        with cumulative ``_bucket{le=...}`` lines and exemplar annotations
        on buckets where a tail-retained trace exists. Never emits NaN
        (empty timers emit count/sum only); every family name carries
        exactly one # TYPE line."""
        sane = sanitize_metric_name
        counters, gauges, timers, values, exemplars = \
            self._export_locked_state()
        lines: List[str] = []
        for name, v in sorted(counters.items()):
            m = sane(name) + "_total"
            lines.append(f"# TYPE {m} counter")
            lines.append(f"{m} {v}")
        for name, g in sorted(gauges.items()):
            m = sane(name)
            # lazily-sampled monotone process totals (process.cpu_seconds_
            # total et al.) register as gauges but ARE counters; the
            # _total suffix is the contract and the exposition honors it
            lines.append(f"# TYPE {m} "
                         f"{'counter' if name.endswith('_total') else 'gauge'}")
            lines.append(f"{m} {float(g):g}")
        for name, (h, buckets, total_s) in sorted(timers.items()):
            m = sane(name) + "_seconds"
            lines.append(f"# TYPE {m} summary")
            if h["count"]:
                for q, key in ((0.5, "p50_ms"), (0.9, "p90_ms"),
                               (0.99, "p99_ms")):
                    lines.append(
                        f'{m}{{quantile="{q}"}} {h[key] / 1000:.9g}')
            lines.append(f"{m}_count {h['count']}")
            lines.append(f"{m}_sum {total_s:.9g}")
            mh = m + "_hist"
            lines.append(f"# TYPE {mh} histogram")
            self._bucket_lines(lines, mh, buckets, h["count"], total_s,
                               exemplars.get(name))
        for name, (h, buckets, total) in sorted(values.items()):
            m = sane(name)  # raw units: no _seconds suffix
            lines.append(f"# TYPE {m} summary")
            if h["count"]:
                for q, key in ((0.5, "p50"), (0.9, "p90"), (0.99, "p99")):
                    lines.append(f'{m}{{quantile="{q}"}} {h[key]:.9g}')
            lines.append(f"{m}_count {h['count']}")
            lines.append(f"{m}_sum {total:.9g}")
            mh = m + "_hist"
            lines.append(f"# TYPE {mh} histogram")
            self._bucket_lines(lines, mh, buckets, h["count"], total, None)
        return "\n".join(lines) + "\n"

    def reset(self) -> None:
        """Clear counters and timers (gauges persist — they describe current
        state, not accumulation). In-flight ``time()`` blocks that entered
        before this reset are discarded at their exit (generation check)."""
        with self._lock:
            self._gen += 1
            self._counters.clear()
            self._timers.clear()
            self._values.clear()
            self._pending.clear()  # same straddling-discard semantics
            self._exemplars.clear()


# process-global default registry (≙ the shared MetricRegistry)
REGISTRY = MetricsRegistry()

_DEVICE_GAUGES_REGISTERED = False


def register_device_gauges(registry: Optional[MetricsRegistry] = None) -> None:
    """Install lazy device + host-pressure gauges: ``device.count``,
    ``device.bytes_in_use`` / ``device.peak_bytes_in_use`` /
    ``device.bytes_limit`` (summed ``memory_stats()`` over
    ``jax.local_devices()`` where the backend reports them — live AND
    peak HBM so an OOM trajectory is visible before it lands), plus
    ``process.rss_bytes`` (host resident set),
    ``process.cpu_seconds_total`` (monotone user+sys CPU, exported as a
    counter), ``trace.ring_depth`` (recent-trace ring occupancy) and
    ``wal.open_segments`` (live WAL segment files) — so /metrics reflects
    host memory and observability-buffer pressure, not just device state.
    Idempotent; probes evaluate at snapshot time and never raise through
    the surface."""
    global _DEVICE_GAUGES_REGISTERED
    reg = registry or REGISTRY
    if reg is REGISTRY and _DEVICE_GAUGES_REGISTERED:
        return
    if reg is REGISTRY:
        _DEVICE_GAUGES_REGISTERED = True

    def _count():
        import jax
        return len(jax.local_devices())

    def _mem_key(key):
        def probe():
            from geomesa_tpu.index.device import memory_snapshot
            return memory_snapshot().get(key)
        return probe

    def _cpu_seconds():
        # user + system CPU of this process — monotone, so the gauge
        # exports as a counter (the _total contract in to_prometheus)
        t = os.times()
        return round(t[0] + t[1], 3)

    def _rss():
        # current (not peak) resident set via /proc; ru_maxrss fallback
        try:
            with open("/proc/self/statm") as fh:
                pages = int(fh.read().split()[1])
            return pages * (os.sysconf("SC_PAGE_SIZE")
                            if hasattr(os, "sysconf") else 4096)
        except OSError:
            import resource
            return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024

    def _ring_depth():
        from geomesa_tpu.trace import RING
        return len(RING)

    def _wal_segments():
        from geomesa_tpu.durability.wal import open_segment_count
        return open_segment_count()

    reg.set_gauge("device.count", _count)
    reg.set_gauge("device.bytes_in_use", _mem_key("bytes_in_use"))
    reg.set_gauge("device.peak_bytes_in_use", _mem_key("peak_bytes_in_use"))
    reg.set_gauge("device.bytes_limit", _mem_key("bytes_limit"))
    reg.set_gauge("process.rss_bytes", _rss)
    reg.set_gauge("process.cpu_seconds_total", _cpu_seconds)
    reg.set_gauge("trace.ring_depth", _ring_depth)
    reg.set_gauge("wal.open_segments", _wal_segments)
