"""Metrics/observability: counters, histogram timers, gauges, reporters.

≙ the reference's converter ingest metrics + audit surface (SURVEY.md §5:
dropwizard metrics with graphite/cloudwatch/ganglia reporters in
geomesa-convert-metrics-*; QueryEvent audit records in index/audit/
QueryEvent.scala:13). Here a process-local registry collects ingest and
query counters/timers; ``snapshot()`` serializes for the CLI/REST surface,
``to_prometheus()`` emits the text exposition format, and ``add_reporter``
hooks a callable for external sinks (the graphite-reporter slot).

Timers are fixed-bucket log-scale histograms (dropwizard's reservoir slot):
bucket upper bounds grow geometrically by 2^0.25 from 1µs, so percentiles
carry ≤ ~19% relative error at O(bytes) cost and zero allocation per
observation. ``percentile()`` returns the UPPER BOUND of the bucket holding
the rank-th observation (deterministic, never an interpolated value that no
observation produced).

Reset semantics (the snapshot/reset race): ``reset()`` bumps a generation
counter; a ``time()`` block that STRADDLES a reset is discarded at exit
rather than resurrecting its name with a lost count — post-reset snapshots
only ever contain observations that started after the reset.
"""

from __future__ import annotations

import bisect
import math
import threading
import time
from collections import defaultdict
from contextlib import contextmanager
from typing import Callable, Dict, List, Optional

# -- histogram geometry ------------------------------------------------------

_BUCKET_MIN_S = 1e-6          # first bucket: everything <= 1µs
_BUCKET_FACTOR = 2.0 ** 0.25  # ~19% resolution per bucket
_N_BUCKETS = 128              # reaches 1e-6 * 2^(127/4) ≈ 3.3e3 s

# upper (inclusive) bound of each bucket; the last is +inf-in-spirit
BUCKET_BOUNDS: tuple = tuple(
    _BUCKET_MIN_S * _BUCKET_FACTOR ** i for i in range(_N_BUCKETS))


def bucket_index(seconds: float) -> int:
    """First bucket whose upper bound >= seconds (exact via bisect — no
    float-log boundary jitter)."""
    i = bisect.bisect_left(BUCKET_BOUNDS, seconds)
    return min(i, _N_BUCKETS - 1)


class Histogram:
    """Log-scale fixed-bucket duration histogram (count/total/max +
    percentiles). Not internally locked — the registry lock covers it."""

    __slots__ = ("count", "total_s", "max_s", "buckets")

    def __init__(self):
        self.count = 0
        self.total_s = 0.0
        self.max_s = 0.0
        self.buckets = [0] * _N_BUCKETS

    def observe(self, seconds: float) -> None:
        self.count += 1
        self.total_s += seconds
        if seconds > self.max_s:
            self.max_s = seconds
        self.buckets[bucket_index(seconds)] += 1

    def percentile(self, q: float) -> float:
        """Upper bound (seconds) of the bucket holding the ceil(q*count)-th
        observation; 0.0 on an empty histogram."""
        if self.count == 0:
            return 0.0
        rank = max(1, math.ceil(q * self.count))
        cum = 0
        for i, c in enumerate(self.buckets):
            cum += c
            if cum >= rank:
                return BUCKET_BOUNDS[i]
        return BUCKET_BOUNDS[-1]

    def to_dict(self) -> dict:
        return {
            "count": self.count,
            "total_s": round(self.total_s, 6),
            "mean_ms": round(self.total_s / self.count * 1000, 3)
            if self.count else 0.0,
            "max_ms": round(self.max_s * 1000, 3),
            "p50_ms": round(self.percentile(0.50) * 1000, 3),
            "p90_ms": round(self.percentile(0.90) * 1000, 3),
            "p99_ms": round(self.percentile(0.99) * 1000, 3),
        }

    def to_value_dict(self) -> dict:
        """Raw-unit summary for value histograms (batch sizes, queue depths —
        anything that isn't a duration; no ms conversion)."""
        return {
            "count": self.count,
            "total": round(self.total_s, 6),
            "mean": round(self.total_s / self.count, 3) if self.count else 0.0,
            "max": round(self.max_s, 3),
            "p50": round(self.percentile(0.50), 3),
            "p90": round(self.percentile(0.90), 3),
            "p99": round(self.percentile(0.99), 3),
        }


class MetricsRegistry:
    """Thread-safe counters + histogram timers + gauges."""

    def __init__(self):
        self._lock = threading.Lock()
        self._gen = 0
        self._counters: Dict[str, int] = defaultdict(int)
        self._timers: Dict[str, Histogram] = defaultdict(Histogram)
        # value histograms: same log-bucket geometry, raw units (batch
        # sizes, flush waits in queries, …) — the scheduler's distribution
        # surface. Buckets start at 1e-6 so any positive value lands exactly.
        self._values: Dict[str, Histogram] = defaultdict(Histogram)
        self._gauges: Dict[str, object] = {}  # value or zero-arg callable
        self._reporters: List[Callable[[str, str, float], None]] = []
        # span trees awaiting histogram feed (GIL-atomic appends from trace
        # close; drained under the lock at snapshot time) — keeps the
        # per-query trace-close cost to one list append
        self._pending: List[object] = []

    def inc(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counters[name] += n
            reporters = list(self._reporters)
        self._report(reporters, "counter", name, n)

    def observe(self, name: str, seconds: float) -> None:
        """Record one duration into the name's histogram (the span feed —
        the µs-scale hot path; skip the reporter copy when there are none)."""
        with self._lock:
            self._timers[name].observe(seconds)
            reporters = list(self._reporters) if self._reporters else None
        if reporters:
            self._report(reporters, "timer", name, seconds)

    def observe_batch(self, pairs) -> None:
        """Record many (name, seconds) at once under ONE lock acquisition."""
        with self._lock:
            for name, seconds in pairs:
                self._timers[name].observe(seconds)
            reporters = list(self._reporters) if self._reporters else None
        if reporters:
            for name, seconds in pairs:
                self._report(reporters, "timer", name, seconds)

    def observe_value(self, name: str, value: float) -> None:
        """Record one raw-unit observation (NOT a duration) into the name's
        value histogram — batch sizes, cover cardinalities, queue depths."""
        with self._lock:
            self._values[name].observe(value)

    def feed_tree(self, root) -> None:
        """Defer a whole span tree (an object with ``walk()`` yielding nodes
        with ``name``/``duration_ms``) to the next drain — the trace-close
        hot-path feed: ONE locked list append now, histogram math at
        snapshot time. Reporters consequently see trace-span timer events at
        drain time (they poll snapshots anyway, the dropwizard model)."""
        with self._lock:
            self._pending.append(root)

    def _drain_locked(self) -> Optional[list]:
        """Fold pending span trees into the histograms (lock held). Returns
        (name, seconds) pairs for the reporter fan-out, or None."""
        if not self._pending:
            return None
        pending, self._pending = self._pending, []
        pairs = [(s.name, s.duration_ms / 1000.0)
                 for root in pending for s in root.walk()]
        for name, seconds in pairs:
            self._timers[name].observe(seconds)
        return pairs if self._reporters else None

    @contextmanager
    def time(self, name: str):
        t0 = time.perf_counter()
        gen = self._gen  # racy read is fine: reset() bumps under the lock,
        # and the exit-side compare re-reads under the lock
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            reporters = None
            with self._lock:
                if self._gen == gen:
                    self._timers[name].observe(dt)
                    reporters = list(self._reporters)
                # else: straddled a reset() — discard, never resurrect
            if reporters is not None:
                self._report(reporters, "timer", name, dt)

    def set_gauge(self, name: str, value) -> None:
        """Set a gauge to a value OR a zero-arg callable evaluated lazily at
        snapshot time (resident rows, device memory, …)."""
        with self._lock:
            self._gauges[name] = value

    @staticmethod
    def _report(reporters, kind: str, name: str, value: float) -> None:
        for r in reporters:
            try:
                r(kind, name, value)
            except Exception:
                pass  # a failing sink must never fail the store (dropwizard rule)

    def add_reporter(self, fn: Callable[[str, str, float], None]) -> None:
        """fn(kind, name, value) — the external-sink slot (graphite/etc.)."""
        with self._lock:
            self._reporters.append(fn)

    def _gauge_values(self) -> Dict[str, float]:
        with self._lock:
            items = list(self._gauges.items())
        out = {}
        for k, v in items:
            if callable(v):
                try:
                    v = v()
                except Exception:
                    continue  # a failing probe must never fail the surface
            if v is not None:
                out[k] = v
        return out

    def snapshot(self) -> dict:
        gauges = self._gauge_values()  # probes run OUTSIDE the lock
        with self._lock:
            pairs = self._drain_locked()
            reporters = list(self._reporters) if pairs else None
            out = {
                "counters": dict(self._counters),
                "timers": {k: h.to_dict() for k, h in self._timers.items()},
                "histograms": {k: h.to_value_dict()
                               for k, h in self._values.items()},
                "gauges": gauges,
            }
        if pairs:
            for name, seconds in pairs:
                self._report(reporters, "timer", name, seconds)
        return out

    def snapshot_prefixed(self, *prefixes: str) -> dict:
        """``snapshot()`` filtered to names under the given prefixes — the
        focused debug surfaces (CLI ``debug admission``/``debug scheduler``,
        web overload state) without the whole registry."""
        snap = self.snapshot()
        return {section: {k: v for k, v in values.items()
                          if k.startswith(prefixes)}
                for section, values in snap.items()}

    def to_prometheus(self) -> str:
        """Prometheus text exposition: counters as *_total, timers as
        summaries with p50/p90/p99 quantiles, gauges as gauges. Never emits
        NaN (empty timers emit count/sum only)."""
        def sane(name: str) -> str:
            return "geomesa_tpu_" + "".join(
                c if c.isalnum() or c == "_" else "_" for c in name)

        snap = self.snapshot()
        lines: List[str] = []
        for name, v in sorted(snap["counters"].items()):
            m = sane(name) + "_total"
            lines.append(f"# TYPE {m} counter")
            lines.append(f"{m} {v}")
        for name, g in sorted(snap["gauges"].items()):
            m = sane(name)
            lines.append(f"# TYPE {m} gauge")
            lines.append(f"{m} {float(g):g}")
        for name, h in sorted(snap["timers"].items()):
            m = sane(name) + "_seconds"
            lines.append(f"# TYPE {m} summary")
            if h["count"]:
                for q, key in ((0.5, "p50_ms"), (0.9, "p90_ms"),
                               (0.99, "p99_ms")):
                    lines.append(
                        f'{m}{{quantile="{q}"}} {h[key] / 1000:.9g}')
            lines.append(f"{m}_count {h['count']}")
            lines.append(f"{m}_sum {h['total_s']:.9g}")
        for name, h in sorted(snap["histograms"].items()):
            m = sane(name)  # raw units: no _seconds suffix
            lines.append(f"# TYPE {m} summary")
            if h["count"]:
                for q, key in ((0.5, "p50"), (0.9, "p90"), (0.99, "p99")):
                    lines.append(f'{m}{{quantile="{q}"}} {h[key]:.9g}')
            lines.append(f"{m}_count {h['count']}")
            lines.append(f"{m}_sum {h['total']:.9g}")
        return "\n".join(lines) + "\n"

    def reset(self) -> None:
        """Clear counters and timers (gauges persist — they describe current
        state, not accumulation). In-flight ``time()`` blocks that entered
        before this reset are discarded at their exit (generation check)."""
        with self._lock:
            self._gen += 1
            self._counters.clear()
            self._timers.clear()
            self._values.clear()
            self._pending.clear()  # same straddling-discard semantics


# process-global default registry (≙ the shared MetricRegistry)
REGISTRY = MetricsRegistry()

_DEVICE_GAUGES_REGISTERED = False


def register_device_gauges(registry: Optional[MetricsRegistry] = None) -> None:
    """Install lazy device gauges: ``device.count`` and
    ``device.bytes_in_use`` (summed ``memory_stats()`` over
    ``jax.local_devices()`` where the backend reports them). Idempotent;
    probes evaluate at snapshot time and never raise through the surface."""
    global _DEVICE_GAUGES_REGISTERED
    reg = registry or REGISTRY
    if reg is REGISTRY and _DEVICE_GAUGES_REGISTERED:
        return
    if reg is REGISTRY:
        _DEVICE_GAUGES_REGISTERED = True

    def _count():
        import jax
        return len(jax.local_devices())

    def _mem():
        import jax
        total, seen = 0, False
        for d in jax.local_devices():
            stats = getattr(d, "memory_stats", None)
            s = stats() if stats is not None else None
            if s and "bytes_in_use" in s:
                total += int(s["bytes_in_use"])
                seen = True
        return total if seen else None

    reg.set_gauge("device.count", _count)
    reg.set_gauge("device.bytes_in_use", _mem)
