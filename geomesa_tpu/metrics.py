"""Metrics/observability: counters, timers, and a pluggable reporter.

≙ the reference's converter ingest metrics + audit surface (SURVEY.md §5:
dropwizard metrics with graphite/cloudwatch/ganglia reporters in
geomesa-convert-metrics-*; QueryEvent audit records in index/audit/
QueryEvent.scala:13). Here a process-local registry collects ingest and
query counters/timers; ``snapshot()`` serializes for the CLI/REST surface,
and ``add_reporter`` hooks a callable for external sinks (the
graphite-reporter slot)."""

from __future__ import annotations

import threading
import time
from collections import defaultdict
from contextlib import contextmanager
from typing import Callable, Dict, List


class MetricsRegistry:
    """Thread-safe counters + duration histograms (count/total/max)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = defaultdict(int)
        self._timers: Dict[str, List[float]] = defaultdict(
            lambda: [0, 0.0, 0.0])  # [count, total_s, max_s]
        self._reporters: List[Callable[[str, str, float], None]] = []

    def inc(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counters[name] += n
            reporters = list(self._reporters)
        self._report(reporters, "counter", name, n)

    @contextmanager
    def time(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            with self._lock:
                t = self._timers[name]
                t[0] += 1
                t[1] += dt
                t[2] = max(t[2], dt)
                reporters = list(self._reporters)
            self._report(reporters, "timer", name, dt)

    @staticmethod
    def _report(reporters, kind: str, name: str, value: float) -> None:
        for r in reporters:
            try:
                r(kind, name, value)
            except Exception:
                pass  # a failing sink must never fail the store (dropwizard rule)

    def add_reporter(self, fn: Callable[[str, str, float], None]) -> None:
        """fn(kind, name, value) — the external-sink slot (graphite/etc.)."""
        with self._lock:
            self._reporters.append(fn)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "counters": dict(self._counters),
                "timers": {
                    k: {"count": int(v[0]), "total_s": round(v[1], 6),
                        "mean_ms": round(v[1] / v[0] * 1000, 3) if v[0] else 0.0,
                        "max_ms": round(v[2] * 1000, 3)}
                    for k, v in self._timers.items()
                },
            }

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._timers.clear()


# process-global default registry (≙ the shared MetricRegistry)
REGISTRY = MetricsRegistry()
