"""Selectivity estimation from cached sketches.

≙ reference `StatsBasedEstimator` (geomesa-index-api/.../stats/
StatsBasedEstimator.scala): spatial selectivity from the Z2 grid histogram,
temporal from the Z3 per-bin histogram, equality from the count-min Frequency,
numeric ranges from binned Histograms. Feeds the cost-based strategy decider
(StrategyDecider.scala:140-168) — plans are priced by estimated matching rows.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from geomesa_tpu.curves.binnedtime import TimePeriod, max_offset, time_to_binned_time
from geomesa_tpu.filter import ir
from geomesa_tpu.filter.extract import extract_bboxes, extract_intervals
from geomesa_tpu.stats import sketches as sk


class StatsBasedEstimator:
    """Estimates matching-row counts for filters against one feature type."""

    def __init__(self, sft, stats: Dict[str, sk.Stat], total: int):
        self.sft = sft
        self.stats = stats
        self.total = total
        geom = sft.geometry_attribute
        dtg = sft.dtg_attribute
        self.geom = geom.name if geom else None
        self.dtg = dtg.name if dtg else None

    def _find(self, kind: str, attr: Optional[str] = None):
        return sk.find_stat(self.stats.values(), kind, attr)

    # -- selectivities (fractions of total) ---------------------------------

    def spatial_selectivity(self, boxes) -> Optional[float]:
        hist: sk.Z2HistogramStat = self._find("z2histogram", self.geom)
        if hist is None or hist.is_empty:
            return None
        mass = sum(hist.mass_in_box(*b) for b in boxes)
        return min(1.0, mass / max(1, self.total))

    def temporal_selectivity(self, intervals) -> Optional[float]:
        hist: sk.Z3HistogramStat = self._find("z3histogram", self.dtg)
        if hist is None or hist.is_empty:
            return None
        period = TimePeriod.parse(hist.period)
        mo = max_offset(period)
        windows = []
        for lo, hi in intervals:
            blo, olo = time_to_binned_time(lo, period)
            bhi, ohi = time_to_binned_time(hi, period)
            windows.append((int(blo), int(olo), int(bhi), int(ohi)))
        return min(1.0, hist.mass_in_windows(windows, mo) / max(1, self.total))

    def equality_selectivity(self, attr: str, value) -> Optional[float]:
        enum: sk.EnumerationStat = self._find("enumeration", attr)
        if enum is not None and not enum.is_empty:
            return enum.counts.get(value, 0) / max(1, self.total)
        freq: sk.FrequencyStat = self._find("frequency", attr)
        if freq is not None and not freq.is_empty:
            return freq.estimate(value) / max(1, self.total)
        mm: sk.MinMaxStat = self._find("minmax", attr)
        if mm is not None and not mm.is_empty:
            return 1.0 / max(1, mm.cardinality)
        return None

    def range_selectivity(self, attr: str, lo, hi) -> Optional[float]:
        hist: sk.HistogramStat = self._find("histogram", attr)
        if hist is None or hist.is_empty:
            return None
        return min(1.0, hist.mass_between(float(lo), float(hi)) / max(1, self.total))

    # -- filter walk ---------------------------------------------------------

    def selectivity(self, f: ir.Filter) -> float:
        """Estimated fraction of rows matching ``f`` (1.0 when unknown —
        conservative superset, like the reference's fallback heuristics)."""
        if isinstance(f, ir.Include):
            return 1.0
        if isinstance(f, ir.Exclude):
            return 0.0
        if isinstance(f, ir.And):
            out = 1.0
            for c in f.children:
                out *= self.selectivity(c)
            return out
        if isinstance(f, ir.Or):
            return min(1.0, sum(self.selectivity(c) for c in f.children))
        if isinstance(f, ir.Not):
            return max(0.0, 1.0 - self.selectivity(f.child))
        if isinstance(f, (ir.BBox, ir.Intersects, ir.Contains, ir.Within, ir.Dwithin)):
            ext = extract_bboxes(f, self.geom)
            if ext.unconstrained or len(ext.boxes) == 0:
                return 1.0
            s = self.spatial_selectivity(ext.boxes)
            return 1.0 if s is None else s
        if isinstance(f, ir.During):
            iv = extract_intervals(f, self.dtg)
            if iv is None or iv.unconstrained:
                return 1.0
            s = self.temporal_selectivity(iv.intervals)
            return 1.0 if s is None else s
        if isinstance(f, ir.Cmp):
            if f.attr == self.dtg:
                iv = extract_intervals(f, self.dtg)
                if iv is not None and not iv.unconstrained and len(iv.intervals):
                    s = self.temporal_selectivity(iv.intervals)
                    if s is not None:
                        return s
            if f.op == "=":
                s = self.equality_selectivity(f.attr, f.value)
                return 1.0 if s is None else s
            if f.op in ("<", "<=", ">", ">="):
                mm: sk.MinMaxStat = self._find("minmax", f.attr)
                if mm is not None and not mm.is_empty and not mm.geometric \
                        and isinstance(f.value, (int, float, np.number)):
                    lo = mm.min if f.op in ("<", "<=") else f.value
                    hi = f.value if f.op in ("<", "<=") else mm.max
                    s = self.range_selectivity(f.attr, lo, hi)
                    if s is not None:
                        return s
                    span = float(mm.max) - float(mm.min)
                    if span > 0:
                        frac = (float(hi) - float(lo)) / span
                        return float(np.clip(frac, 0.0, 1.0))
                return 0.5
            if f.op == "<>":
                s = self.equality_selectivity(f.attr, f.value)
                return 1.0 if s is None else max(0.0, 1.0 - s)
        if isinstance(f, ir.In):
            ss = [self.equality_selectivity(f.attr, v) for v in f.values]
            known = [s for s in ss if s is not None]
            if known:
                return min(1.0, sum(known) + (len(ss) - len(known)) * 0.1)
            return 1.0
        if isinstance(f, ir.FidFilter):
            return min(1.0, len(f.fids) / max(1, self.total))
        return 1.0

    def estimate_count(self, f: ir.Filter) -> int:
        return int(round(self.selectivity(f) * self.total))
