"""Stat DSL parser + columnar observation driver.

≙ the reference's parser-combinator Stat spec grammar (utils/stats/
Stat.scala:40-131): semicolon-separated ``Name(args)`` calls, attribute names
quoted. Examples accepted here exactly as there::

    Count()
    MinMax("dtg");Count()
    Enumeration("name");TopK("name")
    Frequency("name",12)
    Histogram("val",20,0,100)
    Z3Histogram("dtg","week")
    GroupBy("cat",Count())

``observe_table`` drives bulk observation from a FeatureTable — each sketch
receives whole numpy columns (geometry → bbox planes / point coords; dtg for
Z3Histogram → exact (bin, offset) decomposition).
"""

from __future__ import annotations

import re
from typing import List, Optional

import numpy as np

from geomesa_tpu.curves.binnedtime import TimePeriod, max_offset, time_to_binned_time
from geomesa_tpu.features.table import FeatureTable, StringColumn
from geomesa_tpu.features.geometry import GeometryArray
from geomesa_tpu.stats import sketches as sk

_CALL = re.compile(r"^\s*(\w+)\s*\(")


def _split_top(s: str, delim: str) -> List[str]:
    """Split on top-level ``delim`` (respects quotes and parens)."""
    out, depth, quote, cur = [], 0, None, []
    for ch in s:
        if quote:
            cur.append(ch)
            if ch == quote:
                quote = None
        elif ch in "\"'":
            quote = ch
            cur.append(ch)
        elif ch == "(":
            depth += 1
            cur.append(ch)
        elif ch == ")":
            depth -= 1
            cur.append(ch)
        elif ch == delim and depth == 0:
            out.append("".join(cur).strip())
            cur = []
        else:
            cur.append(ch)
    if cur:
        out.append("".join(cur).strip())
    return [a for a in out if a]


def _split_args(body: str) -> List[str]:
    return _split_top(body, ",")


def _split_calls(spec: str) -> List[str]:
    return _split_top(spec, ";")


def _unquote(s: str) -> str:
    s = s.strip()
    if len(s) >= 2 and s[0] in "\"'" and s[-1] == s[0]:
        return s[1:-1]
    return s


def parse_stat(spec: str) -> sk.Stat:
    """Parse a Stat DSL string into a sketch (SeqStat when ';'-separated)."""
    calls = _split_calls(spec)
    if not calls:
        raise ValueError(f"Empty stat spec: {spec!r}")
    stats = [_parse_one(c) for c in calls]
    return stats[0] if len(stats) == 1 else sk.SeqStat(stats)


def _parse_one(call: str) -> sk.Stat:
    m = _CALL.match(call)
    if not m or not call.rstrip().endswith(")"):
        raise ValueError(f"Invalid stat call: {call!r}")
    name = m.group(1)
    body = call[m.end(): call.rstrip().rfind(")")]
    args = _split_args(body)
    if name == "Count":
        return sk.CountStat()
    if name == "MinMax":
        return sk.MinMaxStat(_unquote(args[0]))
    if name == "Enumeration":
        return sk.EnumerationStat(_unquote(args[0]))
    if name == "TopK":
        return sk.TopKStat(_unquote(args[0]))
    if name == "Frequency":
        return sk.FrequencyStat(_unquote(args[0]),
                                int(args[1]) if len(args) > 1 else 12)
    if name == "Histogram":
        return sk.HistogramStat(_unquote(args[0]), int(args[1]),
                                float(args[2]), float(args[3]))
    if name == "Z2Histogram":
        return sk.Z2HistogramStat(_unquote(args[0]),
                                  int(args[1]) if len(args) > 1 else 5)
    if name == "Z3Histogram":
        return sk.Z3HistogramStat(_unquote(args[0]),
                                  _unquote(args[1]) if len(args) > 1 else "week")
    if name == "DescriptiveStats":
        return sk.DescriptiveStat([_unquote(a) for a in args])
    if name == "GroupBy":
        return sk.GroupByStat(_unquote(args[0]), ",".join(args[1:]))
    raise ValueError(f"Unknown stat: {name!r}")


# -- columnar observation ----------------------------------------------------


def _raw_column(table: FeatureTable, attr: str) -> np.ndarray:
    col = table.columns[attr]
    if isinstance(col, StringColumn):
        return np.asarray(col.vocab, dtype=object)[col.codes]
    if isinstance(col, GeometryArray):
        raise TypeError("geometry columns are observed via bbox/point paths")
    return np.asarray(col)


def observe_table(stat: sk.Stat, table: FeatureTable,
                  mask: Optional[np.ndarray] = None) -> sk.Stat:
    """Observe every row of ``table`` (optionally mask-filtered) into ``stat``."""
    sub = table if mask is None else table.take(np.nonzero(mask)[0])
    n = len(sub)
    if isinstance(stat, sk.SeqStat):
        for s in stat.stats:
            observe_table(s, sub)
        return stat
    if isinstance(stat, sk.CountStat):
        stat.observe(n)
        return stat
    if isinstance(stat, sk.Z3HistogramStat):
        period = TimePeriod.parse(stat.period)
        ms = np.asarray(sub.columns[stat.dtg], dtype=np.int64)
        bins, offs = time_to_binned_time(ms, period)
        stat.observe(bins, offs, max_offset(period))
        return stat
    if isinstance(stat, sk.Z2HistogramStat):
        garr = sub.columns[stat.attr]
        if garr.is_points:
            x, y = garr.point_xy()
        else:
            bb = garr.bboxes()
            x, y = (bb[:, 0] + bb[:, 2]) / 2, (bb[:, 1] + bb[:, 3]) / 2
        stat.observe(x, y)
        return stat
    if isinstance(stat, sk.MinMaxStat):
        col = sub.columns[stat.attr]
        if isinstance(col, GeometryArray):
            stat.geometric = True
            bb = col.bboxes()
            stat.observe(bb[:, 0], bb[:, 1], bb[:, 2], bb[:, 3])
        else:
            stat.observe(_raw_column(sub, stat.attr))
        return stat
    if isinstance(stat, sk.GroupByStat):
        sub_attrs = stat._template.attrs
        stat.observe(_raw_column(sub, stat.attr),
                     *[_raw_column(sub, a) for a in sub_attrs])
        return stat
    stat.observe(*[_raw_column(sub, a) for a in stat.attrs])
    return stat
