"""GeoMesaStats facade: cached sketches + exact stat scans.

≙ reference `GeoMesaStats` API (geomesa-index-api/.../stats/
GeoMesaStats.scala:30,51-160 — getCount/getBounds/getMinMax/getFrequency/
getTopK/getHistogram with exact|estimated modes) and `MetadataBackedStats`
(MetadataBackedStats.scala:36 — sketches recomputed on write and persisted
with the catalog). Here the durable copy is the JSON-safe ``to_dict`` form
(checkpointed with the catalog); the exact path runs the query engine's
device scan to select rows, then bulk-observes the survivors with vectorized
numpy — the filter *is* the expensive part and it runs on the TPU.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Union

import numpy as np

from geomesa_tpu.features.table import FeatureTable, StringColumn
from geomesa_tpu.features.geometry import GeometryArray
from geomesa_tpu.filter import ir
from geomesa_tpu.filter.parser import parse_ecql
from geomesa_tpu.stats import sketches as sk
from geomesa_tpu.stats.dsl import observe_table, parse_stat
from geomesa_tpu.stats.estimator import StatsBasedEstimator

_NUMERIC = {"Int", "Integer", "Long", "Float", "Double"}


def default_stat_specs(sft) -> List[str]:
    """The per-type sketch battery computed on write (≙ the stats that
    MetadataBackedStats.writeStat maintains: count, bounds, histograms,
    frequencies for indexed attributes)."""
    specs = ["Count()"]
    geom = sft.geometry_attribute
    dtg = sft.dtg_attribute
    if geom is not None:
        specs.append(f'MinMax("{geom.name}")')
        specs.append(f'Z2Histogram("{geom.name}",5)')
    if dtg is not None:
        specs.append(f'MinMax("{dtg.name}")')
        specs.append(f'Z3Histogram("{dtg.name}","{sft.z3_interval}")')
    for a in sft.attributes:
        if a.is_geometry or (dtg is not None and a.name == dtg.name):
            continue
        specs.append(f'MinMax("{a.name}")')
        if a.type_name == "String":
            specs.append(f'Frequency("{a.name}",12)')
            specs.append(f'TopK("{a.name}")')
    return specs


class GeoMesaStats:
    """Per-feature-type stats: cached estimates + exact scans."""

    def __init__(self, sft, planner=None):
        self.sft = sft
        self.planner = planner  # set by the datastore after index build
        self.cached: Dict[str, sk.Stat] = {}

    # -- write path (≙ statUpdater.add + flush) ------------------------------

    def update(self, table: FeatureTable) -> None:
        """Recompute the default sketch battery over the full table (called
        on writer flush; bulk recompute replaces the reference's incremental
        observe since the columnar build is itself a bulk operation)."""
        self.cached = {}
        for spec in default_stat_specs(self.sft):
            stat = parse_stat(spec)
            observe_table(stat, table)
            self.cached[spec] = stat

    # -- estimation ----------------------------------------------------------

    @property
    def total(self) -> int:
        c = self.cached.get("Count()")
        return c.count if isinstance(c, sk.CountStat) else 0

    @property
    def estimator(self) -> StatsBasedEstimator:
        return StatsBasedEstimator(self.sft, self.cached, self.total)

    # -- GeoMesaStats API ----------------------------------------------------

    def get_count(self, f: Union[str, ir.Filter, None] = None,
                  exact: bool = False) -> int:
        f = self._filter(f)
        if isinstance(f, ir.Include) and not exact:
            return self.total
        if exact:
            return self.planner.count(f)
        return self.estimator.estimate_count(f)

    def get_bounds(self, f=None, exact: bool = False):
        """(xmin, ymin, xmax, ymax) of the geometry attribute."""
        geom = self.sft.geometry_attribute
        if geom is None:
            return None
        if not exact:
            mm = self._cached_minmax(geom.name)
            if mm is not None and not mm.is_empty:
                return (mm.min[0], mm.min[1], mm.max[0], mm.max[1])
        stat = self.run_stat(f'MinMax("{geom.name}")', f)
        if stat.is_empty:
            return None
        return (stat.min[0], stat.min[1], stat.max[0], stat.max[1])

    def get_min_max(self, attr: str, f=None, exact: bool = False) -> Optional[sk.MinMaxStat]:
        if not exact:
            mm = self._cached_minmax(attr)
            if mm is not None:
                return mm
        return self.run_stat(f'MinMax("{attr}")', f)

    def get_frequency(self, attr: str, f=None, exact: bool = False):
        if not exact:
            fr = self._find_cached("frequency", attr)
            if fr is not None:
                return fr
        return self.run_stat(f'Frequency("{attr}",12)', f)

    def get_top_k(self, attr: str, f=None, exact: bool = False):
        if not exact:
            tk = self._find_cached("topk", attr)
            if tk is not None:
                return tk
        return self.run_stat(f'TopK("{attr}")', f)

    def get_enumeration(self, attr: str, f=None):
        return self.run_stat(f'Enumeration("{attr}")', f)

    def get_histogram(self, attr: str, bins: int = 20, f=None) -> Optional[sk.HistogramStat]:
        """Always an exact scan — endpoints come from the cached MinMax."""
        mm = self.get_min_max(attr, exact=False)
        if mm is None or mm.is_empty or mm.geometric \
                or not isinstance(mm.min, (int, float)):
            return None  # only numeric/date attributes are binnable
        lo, hi = float(mm.min), float(mm.max)
        if hi <= lo:
            hi = lo + 1.0
        return self.run_stat(f'Histogram("{attr}",{bins},{lo},{hi})', f)

    # -- exact stat scans (≙ StatsScan) --------------------------------------

    def run_stat(self, spec: str, f=None, auths=None) -> sk.Stat:
        """Compute a stat over rows matching ``f`` (≙ StatsScan): device
        reductions where the sketch kind supports them, select+observe for
        the rest (see aggregates.stats_scan). ``auths`` restricts to visible
        rows via the device visibility mask."""
        from geomesa_tpu.aggregates.stats_scan import run_stat as _run
        if self.planner is None:
            raise ValueError("stats not attached to a planner")
        return _run(self.planner, spec, self._filter(f), auths=auths)

    # -- helpers -------------------------------------------------------------

    def _filter(self, f) -> ir.Filter:
        if f is None:
            return ir.Include()
        if isinstance(f, str):
            return parse_ecql(f)
        return f

    def _cached_minmax(self, attr: str) -> Optional[sk.MinMaxStat]:
        return self._find_cached("minmax", attr)

    def _find_cached(self, kind: str, attr: str):
        return sk.find_stat(self.cached.values(), kind, attr)

    # -- persistence (checkpointed with the catalog) -------------------------

    def to_dict(self) -> dict:
        return {spec: stat.to_dict() for spec, stat in self.cached.items()}

    @classmethod
    def from_dict(cls, sft, d: dict, planner=None) -> "GeoMesaStats":
        out = cls(sft, planner)
        out.cached = {spec: sk.from_dict(sd) for spec, sd in d.items()}
        return out
