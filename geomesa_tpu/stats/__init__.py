"""Stats subsystem: sketches, DSL, estimation, exact scans.

≙ reference `geomesa-utils/stats` + `geomesa-index-api/stats` (SURVEY.md
§2.5): the Stat sketch family with a parse-able DSL, cached per-type
summaries maintained on write, selectivity estimation for cost-based query
planning, and exact stat computation driven through the scan engine.
"""

from geomesa_tpu.stats.dsl import observe_table, parse_stat
from geomesa_tpu.stats.estimator import StatsBasedEstimator
from geomesa_tpu.stats.sketches import (
    CountStat, DescriptiveStat, EnumerationStat, FrequencyStat, GroupByStat,
    HistogramStat, HyperLogLog, MinMaxStat, SeqStat, Stat, TopKStat,
    Z2HistogramStat, Z3HistogramStat, from_dict,
)
from geomesa_tpu.stats.store import GeoMesaStats, default_stat_specs

__all__ = [
    "CountStat", "DescriptiveStat", "EnumerationStat", "FrequencyStat",
    "GeoMesaStats", "GroupByStat", "HistogramStat", "HyperLogLog",
    "MinMaxStat", "SeqStat", "Stat", "StatsBasedEstimator", "TopKStat",
    "Z2HistogramStat", "Z3HistogramStat", "default_stat_specs", "from_dict",
    "observe_table", "parse_stat",
]
