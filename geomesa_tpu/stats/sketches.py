"""Stat sketches — the summary statistics family.

≙ reference `Stat` hierarchy (/root/reference/geomesa-utils/.../stats/
Stat.scala:40-131, MinMax.scala:30, Histogram.scala:34, Frequency.scala:42,
TopK.scala:24, Z3Histogram.scala:33) and the vendored HyperLogLog
(utils/clearspring). Re-designed for columnar bulk observation: every sketch
has a vectorized ``observe(values)`` over whole numpy columns (the reference
observes one SimpleFeature at a time — a per-row loop would throw away the
columnar layout), plus ``merge`` (``+=``) for cross-device/cross-partition
combination and JSON-safe ``to_dict``/``from_dict`` round-tripping (the
reference's serialize/deserialize + toJson contract).
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

# -- deterministic 64-bit hashing (process-stable: sketches must merge across
#    hosts/runs, so Python's salted hash() is out) ---------------------------

_U = np.uint64


def _splitmix64(x: np.ndarray) -> np.ndarray:
    x = x + _U(0x9E3779B97F4A7C15)
    x = (x ^ (x >> _U(30))) * _U(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> _U(27))) * _U(0x94D049BB133111EB)
    return x ^ (x >> _U(31))


def hash64(values: np.ndarray) -> np.ndarray:
    """Deterministic uint64 hashes for a column of values."""
    arr = np.asarray(values)
    if arr.dtype.kind in "OU":  # strings: blake2b over unique values
        uniq, inverse = np.unique(arr.astype(object), return_inverse=True)
        digests = np.array(
            [int.from_bytes(hashlib.blake2b(str(u).encode(), digest_size=8).digest(), "little")
             for u in uniq], dtype=np.uint64)
        return digests[inverse]
    if arr.dtype.kind == "f":
        arr = np.where(arr == 0.0, 0.0, arr)  # canonicalize -0.0
        bits = arr.astype(np.float64).view(np.uint64)
        return _splitmix64(bits)
    if arr.dtype.kind == "b":
        arr = arr.astype(np.uint64)
    with np.errstate(over="ignore"):
        return _splitmix64(arr.astype(np.int64).view(np.uint64))


# -- base --------------------------------------------------------------------


class Stat:
    """Base sketch. Subclasses define kind, observe, merge, to/from_dict."""

    kind = "stat"
    attrs: Tuple[str, ...] = ()

    def observe(self, *columns: np.ndarray) -> None:
        raise NotImplementedError

    def __iadd__(self, other: "Stat") -> "Stat":
        raise NotImplementedError

    def __add__(self, other: "Stat") -> "Stat":
        out = from_dict(self.to_dict())
        out += other
        return out

    @property
    def is_empty(self) -> bool:
        raise NotImplementedError

    def to_dict(self) -> dict:
        raise NotImplementedError

    def to_json(self) -> dict:
        """Human-readable summary (≙ Stat.toJson)."""
        return self.to_dict()

    def spec(self) -> str:
        """Round-trippable DSL string for this sketch."""
        raise NotImplementedError


_REGISTRY: Dict[str, type] = {}


def register(cls):
    _REGISTRY[cls.kind] = cls
    return cls


def from_dict(d: dict) -> Stat:
    return _REGISTRY[d["kind"]]._from_dict(d)


def _json_key(v):
    return v.item() if isinstance(v, np.generic) else v


def find_stat(stats, kind: str, attr: Optional[str] = None) -> Optional["Stat"]:
    """Find the first leaf sketch of ``kind`` (optionally over ``attr``) in an
    iterable of stats, descending into SeqStats."""
    for s in stats:
        for leaf in (s.stats if isinstance(s, SeqStat) else [s]):
            if leaf.kind == kind and (attr is None or attr in leaf.attrs):
                return leaf
    return None


# -- Count -------------------------------------------------------------------


@register
class CountStat(Stat):
    """Row count (≙ stats/CountStat)."""

    kind = "count"

    def __init__(self, count: int = 0):
        self.count = int(count)

    def observe(self, n_or_column) -> None:
        if np.isscalar(n_or_column):
            self.count += int(n_or_column)
        else:
            self.count += len(n_or_column)

    def __iadd__(self, other):
        self.count += other.count
        return self

    @property
    def is_empty(self):
        return self.count == 0

    def to_dict(self):
        return {"kind": self.kind, "count": self.count}

    @classmethod
    def _from_dict(cls, d):
        return cls(d["count"])

    def spec(self):
        return "Count()"


# -- HyperLogLog (cardinality, used inside MinMax) ---------------------------


class HyperLogLog:
    """Dense HLL, p=11 (2048 registers) — ≙ the vendored clearspring HLL
    backing MinMax cardinality (utils/clearspring, SURVEY.md §2.5)."""

    P = 11
    M = 1 << P

    def __init__(self, registers: Optional[np.ndarray] = None):
        self.registers = (np.zeros(self.M, dtype=np.uint8)
                          if registers is None else registers.astype(np.uint8))

    def observe_hashes(self, h: np.ndarray) -> None:
        if len(h) == 0:
            return
        idx = (h >> _U(64 - self.P)).astype(np.int64)
        rem = (h & _U((1 << (64 - self.P)) - 1)).astype(np.uint64)
        # rank = leading zeros of the (64-P)-bit remainder + 1
        nbits = 64 - self.P
        bl = np.zeros(len(rem), dtype=np.int64)
        nz = rem > 0
        # remainder < 2^53 → exact in f64; frexp exponent = bit length
        bl[nz] = np.frexp(rem[nz].astype(np.float64))[1]
        rank = (nbits - bl + 1).astype(np.uint8)
        np.maximum.at(self.registers, idx, rank)

    def merge(self, other: "HyperLogLog") -> None:
        np.maximum(self.registers, other.registers, out=self.registers)

    def cardinality(self) -> int:
        m = float(self.M)
        alpha = 0.7213 / (1 + 1.079 / m)
        est = alpha * m * m / float(np.sum(np.ldexp(1.0, -self.registers.astype(np.int64))))
        zeros = int(np.sum(self.registers == 0))
        if est <= 2.5 * m and zeros > 0:
            est = m * np.log(m / zeros)  # linear counting
        return int(round(est))


# -- MinMax ------------------------------------------------------------------


@register
class MinMaxStat(Stat):
    """Min/max + HLL cardinality for one attribute (≙ MinMax.scala:30).
    Works for numeric, date (int64 ms), string, and geometry (observe with
    bbox columns xmin,ymin,xmax,ymax → envelope union)."""

    kind = "minmax"

    def __init__(self, attr: str, geometric: bool = False):
        self.attrs = (attr,)
        self.attr = attr
        self.geometric = geometric
        self.min = None
        self.max = None
        self.hll = HyperLogLog()

    def observe(self, values, *extra) -> None:
        if self.geometric:
            xmin, ymin, xmax, ymax = (values, *extra)
            if len(xmin) == 0:
                return
            lo = (float(np.min(xmin)), float(np.min(ymin)))
            hi = (float(np.max(xmax)), float(np.max(ymax)))
            self.min = lo if self.min is None else (min(self.min[0], lo[0]), min(self.min[1], lo[1]))
            self.max = hi if self.max is None else (max(self.max[0], hi[0]), max(self.max[1], hi[1]))
            cx = (np.asarray(xmin) + np.asarray(xmax)) / 2
            cy = (np.asarray(ymin) + np.asarray(ymax)) / 2
            self.hll.observe_hashes(hash64(np.round(cx, 6) * 1e6 + np.round(cy, 6)))
            return
        arr = np.asarray(values)
        if len(arr) == 0:
            return
        lo, hi = np.min(arr), np.max(arr)
        if arr.dtype.kind in "OU":
            lo, hi = str(lo), str(hi)
            self.min = lo if self.min is None else min(self.min, lo)
            self.max = hi if self.max is None else max(self.max, hi)
        else:
            lo, hi = _json_key(lo), _json_key(hi)
            self.min = lo if self.min is None else min(self.min, lo)
            self.max = hi if self.max is None else max(self.max, hi)
        self.hll.observe_hashes(hash64(arr))

    @property
    def cardinality(self) -> int:
        return self.hll.cardinality()

    @property
    def bounds(self):
        return (self.min, self.max)

    def __iadd__(self, other):
        if other.min is not None:
            if self.min is None:
                self.min, self.max = other.min, other.max
            elif self.geometric:
                self.min = (min(self.min[0], other.min[0]), min(self.min[1], other.min[1]))
                self.max = (max(self.max[0], other.max[0]), max(self.max[1], other.max[1]))
            else:
                self.min = min(self.min, other.min)
                self.max = max(self.max, other.max)
        self.hll.merge(other.hll)
        return self

    @property
    def is_empty(self):
        return self.min is None

    def to_dict(self):
        return {"kind": self.kind, "attr": self.attr, "geometric": self.geometric,
                "min": list(self.min) if self.geometric and self.min else self.min,
                "max": list(self.max) if self.geometric and self.max else self.max,
                "registers": self.hll.registers.tolist()}

    def to_json(self):
        return {"kind": self.kind, "attr": self.attr, "min": self.min,
                "max": self.max, "cardinality": self.cardinality}

    @classmethod
    def _from_dict(cls, d):
        out = cls(d["attr"], d.get("geometric", False))
        out.min = tuple(d["min"]) if out.geometric and d["min"] else d["min"]
        out.max = tuple(d["max"]) if out.geometric and d["max"] else d["max"]
        out.hll = HyperLogLog(np.asarray(d["registers"], dtype=np.uint8))
        return out

    def spec(self):
        return f'MinMax("{self.attr}")'


# -- Enumeration (exact value counts) ----------------------------------------


@register
class EnumerationStat(Stat):
    """Exact value→count map (≙ EnumerationStat)."""

    kind = "enumeration"

    def __init__(self, attr: str):
        self.attrs = (attr,)
        self.attr = attr
        self.counts: Dict[object, int] = {}

    def observe(self, values) -> None:
        uniq, cnt = np.unique(np.asarray(values), return_counts=True)
        for v, c in zip(uniq, cnt):
            v = _json_key(v)
            self.counts[v] = self.counts.get(v, 0) + int(c)

    def __iadd__(self, other):
        for v, c in other.counts.items():
            self.counts[v] = self.counts.get(v, 0) + c
        return self

    @property
    def is_empty(self):
        return not self.counts

    def to_dict(self):
        return {"kind": self.kind, "attr": self.attr,
                "values": [[v, c] for v, c in self.counts.items()]}

    @classmethod
    def _from_dict(cls, d):
        out = cls(d["attr"])
        out.counts = {v: c for v, c in d["values"]}
        return out

    def spec(self):
        return f'Enumeration("{self.attr}")'


# -- TopK (space-saving) -----------------------------------------------------


@register
class TopKStat(Stat):
    """Approximate heavy hitters via space-saving (≙ TopK.scala:24, which
    wraps a StreamSummary)."""

    kind = "topk"
    CAPACITY = 128

    def __init__(self, attr: str):
        self.attrs = (attr,)
        self.attr = attr
        self.counts: Dict[object, int] = {}

    def observe(self, values) -> None:
        uniq, cnt = np.unique(np.asarray(values), return_counts=True)
        order = np.argsort(-cnt)
        for i in order:
            v, c = _json_key(uniq[i]), int(cnt[i])
            if v in self.counts:
                self.counts[v] += c
            elif len(self.counts) < self.CAPACITY:
                self.counts[v] = c
            else:
                evict = min(self.counts, key=self.counts.get)
                base = self.counts.pop(evict)
                self.counts[v] = base + c

    def topk(self, k: int = 10) -> List[Tuple[object, int]]:
        return sorted(self.counts.items(), key=lambda kv: -kv[1])[:k]

    def __iadd__(self, other):
        for v, c in sorted(other.counts.items(), key=lambda kv: -kv[1]):
            if v in self.counts:
                self.counts[v] += c
            elif len(self.counts) < self.CAPACITY:
                self.counts[v] = c
            else:
                evict = min(self.counts, key=self.counts.get)
                base = self.counts.pop(evict)
                self.counts[v] = base + c
        return self

    @property
    def is_empty(self):
        return not self.counts

    def to_dict(self):
        return {"kind": self.kind, "attr": self.attr,
                "values": [[v, c] for v, c in self.counts.items()]}

    def to_json(self):
        return {"kind": self.kind, "attr": self.attr, "topk": self.topk()}

    @classmethod
    def _from_dict(cls, d):
        out = cls(d["attr"])
        out.counts = {v: c for v, c in d["values"]}
        return out

    def spec(self):
        return f'TopK("{self.attr}")'


# -- Frequency (count-min sketch) --------------------------------------------


@register
class FrequencyStat(Stat):
    """Count-min sketch (≙ Frequency.scala:42 / RichCountMinSketch)."""

    kind = "frequency"
    DEPTH = 4

    def __init__(self, attr: str, width_bits: int = 12):
        self.attrs = (attr,)
        self.attr = attr
        self.width_bits = width_bits
        self.width = 1 << width_bits
        self.table = np.zeros((self.DEPTH, self.width), dtype=np.int64)
        self.total = 0

    def _rows(self, h: np.ndarray) -> np.ndarray:
        """(DEPTH, n) bucket indices."""
        return np.stack([
            (_splitmix64(h ^ _U((0xA076_1D64_78BD_642F * (i + 1)) & 0xFFFF_FFFF_FFFF_FFFF))
             % _U(self.width)).astype(np.int64)
            for i in range(self.DEPTH)])

    def observe(self, values) -> None:
        arr = np.asarray(values)
        if len(arr) == 0:
            return
        rows = self._rows(hash64(arr))
        for i in range(self.DEPTH):
            np.add.at(self.table[i], rows[i], 1)
        self.total += len(arr)

    def estimate(self, value) -> int:
        h = hash64(np.asarray([value]))
        rows = self._rows(h)
        return int(min(self.table[i, rows[i, 0]] for i in range(self.DEPTH)))

    def __iadd__(self, other):
        self.table += other.table
        self.total += other.total
        return self

    @property
    def is_empty(self):
        return self.total == 0

    def to_dict(self):
        return {"kind": self.kind, "attr": self.attr, "width_bits": self.width_bits,
                "total": self.total, "table": self.table.ravel().tolist()}

    def to_json(self):
        return {"kind": self.kind, "attr": self.attr, "total": self.total}

    @classmethod
    def _from_dict(cls, d):
        out = cls(d["attr"], d["width_bits"])
        out.table = np.asarray(d["table"], dtype=np.int64).reshape(cls.DEPTH, out.width)
        out.total = d["total"]
        return out

    def spec(self):
        return f'Frequency("{self.attr}",{self.width_bits})'


# -- Histogram (binned range counts) -----------------------------------------


@register
class HistogramStat(Stat):
    """Fixed-bin histogram over [lo, hi]; outliers clamp into the end bins
    (≙ Histogram.scala:34 BinnedArray semantics)."""

    kind = "histogram"

    def __init__(self, attr: str, bins: int, lo: float, hi: float):
        self.attrs = (attr,)
        self.attr = attr
        self.bins = int(bins)
        self.lo = float(lo)
        self.hi = float(hi)
        self.counts = np.zeros(self.bins, dtype=np.int64)

    def observe(self, values) -> None:
        arr = np.asarray(values, dtype=np.float64)
        if len(arr) == 0:
            return
        span = self.hi - self.lo
        idx = np.clip(((arr - self.lo) / span * self.bins).astype(np.int64),
                      0, self.bins - 1)
        self.counts += np.bincount(idx, minlength=self.bins)

    def bin_edges(self) -> np.ndarray:
        return np.linspace(self.lo, self.hi, self.bins + 1)

    def mass_between(self, lo: float, hi: float) -> float:
        """Estimated count in [lo, hi] (fractional end bins)."""
        edges = self.bin_edges()
        frac = np.clip((np.minimum(hi, edges[1:]) - np.maximum(lo, edges[:-1]))
                       / (edges[1:] - edges[:-1]), 0.0, 1.0)
        return float(np.sum(self.counts * frac))

    def __iadd__(self, other):
        self.counts += other.counts
        return self

    @property
    def is_empty(self):
        return int(self.counts.sum()) == 0

    def to_dict(self):
        return {"kind": self.kind, "attr": self.attr, "bins": self.bins,
                "lo": self.lo, "hi": self.hi, "counts": self.counts.tolist()}

    def to_json(self):
        return {"kind": self.kind, "attr": self.attr, "bins": self.bins,
                "lo": self.lo, "hi": self.hi, "total": int(self.counts.sum())}

    @classmethod
    def _from_dict(cls, d):
        out = cls(d["attr"], d["bins"], d["lo"], d["hi"])
        out.counts = np.asarray(d["counts"], dtype=np.int64)
        return out

    def spec(self):
        return f'Histogram("{self.attr}",{self.bins},{self.lo},{self.hi})'


# -- Z2Histogram (spatial grid) ----------------------------------------------


@register
class Z2HistogramStat(Stat):
    """2-D lon/lat grid counts at 2^g × 2^g resolution — the spatial
    selectivity surface (≙ the reference's geometry Histogram binned on Z2,
    used by StatsBasedEstimator for spatial estimates). Stored as an (iy, ix)
    grid: box-mass queries are sub-grid sums."""

    kind = "z2histogram"

    def __init__(self, attr: str, gbits: int = 5):
        self.attrs = (attr,)
        self.attr = attr
        self.gbits = int(gbits)
        self.g = 1 << self.gbits
        self.counts = np.zeros((self.g, self.g), dtype=np.int64)

    def observe(self, x: np.ndarray, y: np.ndarray) -> None:
        if len(x) == 0:
            return
        ix = np.clip(((np.asarray(x, np.float64) + 180.0) / 360.0 * self.g).astype(np.int64), 0, self.g - 1)
        iy = np.clip(((np.asarray(y, np.float64) + 90.0) / 180.0 * self.g).astype(np.int64), 0, self.g - 1)
        np.add.at(self.counts, (iy, ix), 1)

    def mass_in_box(self, xmin, ymin, xmax, ymax) -> float:
        """Estimated count inside the bbox (fractional edge cells)."""
        cw, ch = 360.0 / self.g, 180.0 / self.g
        x0 = np.clip((xmin + 180.0) / cw, 0, self.g)
        x1 = np.clip((xmax + 180.0) / cw, 0, self.g)
        y0 = np.clip((ymin + 90.0) / ch, 0, self.g)
        y1 = np.clip((ymax + 90.0) / ch, 0, self.g)
        fx = np.clip(np.minimum(x1, np.arange(1, self.g + 1)) - np.maximum(x0, np.arange(self.g)), 0, 1)
        fy = np.clip(np.minimum(y1, np.arange(1, self.g + 1)) - np.maximum(y0, np.arange(self.g)), 0, 1)
        return float(fy @ self.counts @ fx)

    def __iadd__(self, other):
        self.counts += other.counts
        return self

    @property
    def is_empty(self):
        return int(self.counts.sum()) == 0

    def to_dict(self):
        return {"kind": self.kind, "attr": self.attr, "gbits": self.gbits,
                "counts": self.counts.ravel().tolist()}

    def to_json(self):
        return {"kind": self.kind, "attr": self.attr, "gbits": self.gbits,
                "total": int(self.counts.sum())}

    @classmethod
    def _from_dict(cls, d):
        out = cls(d["attr"], d["gbits"])
        out.counts = np.asarray(d["counts"], dtype=np.int64).reshape(out.g, out.g)
        return out

    def spec(self):
        return f'Z2Histogram("{self.attr}",{self.gbits})'


# -- Z3Histogram (per-epoch temporal buckets) --------------------------------


@register
class Z3HistogramStat(Stat):
    """Per time-bin offset histograms (≙ Z3Histogram.scala:33): counts[bin]
    is a BUCKETS-long histogram over the period offset. Temporal selectivity
    = mass of the query windows."""

    kind = "z3histogram"
    BUCKETS = 64

    def __init__(self, dtg: str, period: str = "week"):
        self.attrs = (dtg,)
        self.dtg = dtg
        self.period = period
        self.bins: Dict[int, np.ndarray] = {}

    def observe(self, bins: np.ndarray, offs: np.ndarray, max_off: int) -> None:
        """bins/offs: the exact (bin, offset) decomposition; max_off: period
        length in offset units."""
        if len(bins) == 0:
            return
        b = np.asarray(bins, dtype=np.int64)
        o = np.clip((np.asarray(offs, np.float64) / max_off * self.BUCKETS).astype(np.int64),
                    0, self.BUCKETS - 1)
        for ub in np.unique(b):
            if ub not in self.bins:
                self.bins[int(ub)] = np.zeros(self.BUCKETS, dtype=np.int64)
            self.bins[int(ub)] += np.bincount(o[b == ub], minlength=self.BUCKETS)

    def mass_in_windows(self, windows: Sequence[Tuple[int, int, int, int]],
                        max_off: int) -> float:
        """windows: (bin_lo, off_lo, bin_hi, off_hi) rows."""
        total = 0.0
        for blo, olo, bhi, ohi in windows:
            # iterate only bins with data — open-ended intervals produce
            # astronomically wide (blo, bhi) spans
            for b in [b for b in self.bins if int(blo) <= b <= int(bhi)]:
                counts = self.bins[b]
                lo = olo / max_off * self.BUCKETS if b == blo else 0.0
                hi = ohi / max_off * self.BUCKETS if b == bhi else float(self.BUCKETS)
                edges = np.arange(self.BUCKETS + 1, dtype=np.float64)
                frac = np.clip(np.minimum(hi, edges[1:]) - np.maximum(lo, edges[:-1]), 0, 1)
                total += float(np.sum(counts * frac))
        return total

    @property
    def total(self) -> int:
        return int(sum(int(c.sum()) for c in self.bins.values()))

    def __iadd__(self, other):
        for b, c in other.bins.items():
            if b in self.bins:
                self.bins[b] += c
            else:
                self.bins[b] = c.copy()
        return self

    @property
    def is_empty(self):
        return not self.bins

    def to_dict(self):
        return {"kind": self.kind, "dtg": self.dtg, "period": self.period,
                "bins": {str(b): c.tolist() for b, c in self.bins.items()}}

    def to_json(self):
        return {"kind": self.kind, "dtg": self.dtg, "period": self.period,
                "bins": sorted(self.bins), "total": self.total}

    @classmethod
    def _from_dict(cls, d):
        out = cls(d["dtg"], d["period"])
        out.bins = {int(b): np.asarray(c, dtype=np.int64) for b, c in d["bins"].items()}
        return out

    def spec(self):
        return f'Z3Histogram("{self.dtg}","{self.period}")'


# -- DescriptiveStats --------------------------------------------------------


@register
class DescriptiveStat(Stat):
    """count/mean/variance/covariance over numeric attributes
    (≙ DescriptiveStats.scala). Accumulates raw power sums (merge = add)."""

    kind = "descriptive"

    def __init__(self, attrs: Sequence[str]):
        self.attrs = tuple(attrs)
        k = len(self.attrs)
        self.n = 0
        self.sum = np.zeros(k)
        self.cross = np.zeros((k, k))  # sum of outer products

    def observe(self, *columns: np.ndarray) -> None:
        x = np.stack([np.asarray(c, dtype=np.float64) for c in columns], axis=1)
        if len(x) == 0:
            return
        self.n += len(x)
        self.sum += x.sum(axis=0)
        self.cross += x.T @ x

    @property
    def mean(self) -> np.ndarray:
        return self.sum / max(self.n, 1)

    @property
    def covariance(self) -> np.ndarray:
        if self.n < 2:
            return np.zeros_like(self.cross)
        m = self.mean
        return (self.cross - self.n * np.outer(m, m)) / (self.n - 1)

    @property
    def variance(self) -> np.ndarray:
        return np.diag(self.covariance)

    def __iadd__(self, other):
        self.n += other.n
        self.sum += other.sum
        self.cross += other.cross
        return self

    @property
    def is_empty(self):
        return self.n == 0

    def to_dict(self):
        return {"kind": self.kind, "attrs": list(self.attrs), "n": self.n,
                "sum": self.sum.tolist(), "cross": self.cross.ravel().tolist()}

    def to_json(self):
        return {"kind": self.kind, "attrs": list(self.attrs), "count": self.n,
                "mean": self.mean.tolist(), "variance": self.variance.tolist()}

    @classmethod
    def _from_dict(cls, d):
        out = cls(d["attrs"])
        out.n = d["n"]
        out.sum = np.asarray(d["sum"])
        k = len(out.attrs)
        out.cross = np.asarray(d["cross"]).reshape(k, k)
        return out

    def spec(self):
        inner = ",".join(f'"{a}"' for a in self.attrs)
        return f"DescriptiveStats({inner})"


# -- GroupBy -----------------------------------------------------------------


@register
class GroupByStat(Stat):
    """Per-group sub-sketches (≙ GroupBy.scala)."""

    kind = "groupby"

    def __init__(self, attr: str, sub_spec: str):
        from geomesa_tpu.stats.dsl import parse_stat  # cycle-free at runtime
        self.attr = attr
        self.sub_spec = sub_spec
        self._template = parse_stat(sub_spec)
        self.attrs = (attr,) + tuple(self._template.attrs)
        self.groups: Dict[object, Stat] = {}

    def observe(self, group_col: np.ndarray, *sub_cols: np.ndarray) -> None:
        from geomesa_tpu.stats.dsl import parse_stat
        g = np.asarray(group_col)
        colmap = dict(zip(self._template.attrs, sub_cols))
        for v in np.unique(g):
            key = _json_key(v)
            sel = g == v
            if key not in self.groups:
                self.groups[key] = parse_stat(self.sub_spec)
            self._observe_sub(self.groups[key], sel, colmap)

    @staticmethod
    def _observe_sub(stat: Stat, sel: np.ndarray, colmap: dict) -> None:
        if isinstance(stat, SeqStat):
            for child in stat.stats:
                GroupByStat._observe_sub(child, sel, colmap)
        elif isinstance(stat, CountStat):
            stat.observe(int(sel.sum()))
        else:
            stat.observe(*[np.asarray(colmap[a])[sel] for a in stat.attrs])

    def __iadd__(self, other):
        for v, s in other.groups.items():
            if v in self.groups:
                self.groups[v] += s
            else:
                self.groups[v] = from_dict(s.to_dict())
        return self

    @property
    def is_empty(self):
        return not self.groups

    def to_dict(self):
        return {"kind": self.kind, "attr": self.attr, "sub_spec": self.sub_spec,
                "groups": [[v, s.to_dict()] for v, s in self.groups.items()]}

    def to_json(self):
        return {"kind": self.kind, "attr": self.attr,
                "groups": {str(v): s.to_json() for v, s in self.groups.items()}}

    @classmethod
    def _from_dict(cls, d):
        out = cls(d["attr"], d["sub_spec"])
        out.groups = {v: from_dict(s) for v, s in d["groups"]}
        return out

    def spec(self):
        return f'GroupBy("{self.attr}",{self.sub_spec})'


# -- SeqStat -----------------------------------------------------------------


@register
class SeqStat(Stat):
    """Ordered list of sketches observed together (≙ SeqStat)."""

    kind = "seq"

    def __init__(self, stats: Sequence[Stat]):
        self.stats = list(stats)
        seen: List[str] = []
        for s in self.stats:
            for a in s.attrs:
                if a not in seen:
                    seen.append(a)
        self.attrs = tuple(seen)

    def __iter__(self):
        return iter(self.stats)

    def __iadd__(self, other):
        for mine, theirs in zip(self.stats, other.stats):
            mine += theirs
        return self

    @property
    def is_empty(self):
        return all(s.is_empty for s in self.stats)

    def to_dict(self):
        return {"kind": self.kind, "stats": [s.to_dict() for s in self.stats]}

    def to_json(self):
        return {"kind": self.kind, "stats": [s.to_json() for s in self.stats]}

    @classmethod
    def _from_dict(cls, d):
        return cls([from_dict(s) for s in d["stats"]])

    def spec(self):
        return ";".join(s.spec() for s in self.stats)
