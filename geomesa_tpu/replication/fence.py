"""Fencing epochs: the split-brain guard for the replicated fleet.

≙ the reference stores' tablet/region fencing (Accumulo's ZooKeeper locks,
HBase's region epochs): at any moment exactly one node may act as primary,
and that right is named by a monotonically increasing **fencing epoch**
persisted next to the durability layout. Every shipped message carries the
sender's epoch; a receiver that has witnessed a higher epoch rejects the
message and answers with the higher epoch, which demotes the stale
would-be primary — so after a partition heals, the loser's writes can
never propagate, and (via the DurabilityManager fence check) the loser
cannot even ack new local writes once it learns it lost.

Promotion = ``bump_epoch`` on the winner: strictly greater than anything
it has seen, fsync-durable before the new primary ships a single frame.
"""

from __future__ import annotations

import json
import os

from geomesa_tpu.durability import rotation

FENCE_FILE = "replication.json"


class FencedError(Exception):
    """A mutation was refused by the fencing check: either this node's
    primary role was superseded by a higher epoch (split-brain loser), or
    the node is a read-only replica."""


def load_epoch(directory: str) -> int:
    """The highest fencing epoch this node has durably witnessed (0 when
    none was ever recorded)."""
    try:
        with open(os.path.join(directory, FENCE_FILE)) as fh:
            return int(json.load(fh).get("epoch", 0))
    except (OSError, ValueError):
        return 0


def save_epoch(directory: str, epoch: int) -> int:
    """Durably record ``epoch`` if it is higher than what is on disk
    (tmp + atomic rename + fsync); returns the resulting on-disk epoch.
    Never moves backwards — a torn adoption must not un-witness an epoch."""
    os.makedirs(directory, exist_ok=True)
    current = load_epoch(directory)
    if epoch <= current:
        return current
    tmp = os.path.join(directory, f".tmp-{FENCE_FILE}")
    with open(tmp, "w") as fh:
        json.dump({"epoch": int(epoch)}, fh)
        rotation.fsync_file(fh)
    os.replace(tmp, os.path.join(directory, FENCE_FILE))
    rotation.fsync_dir(directory)
    return int(epoch)


def bump_epoch(directory: str, at_least: int = 0) -> int:
    """Claim a NEW epoch strictly above both the on-disk record and
    ``at_least`` (the highest epoch the promoting node saw in traffic) —
    the promotion step."""
    new = max(load_epoch(directory), int(at_least)) + 1
    return save_epoch(directory, new)
