"""Follower: a read replica kept consistent by applying shipped WAL frames.

The follower opens its OWN durable store directory (an independent copy of
the data), connects to the primary's LogShipper, and for every shipped
frame: re-verifies the CRC, appends the identical bytes to its local WAL
(log-then-apply — a follower crash at any boundary recovers through the
ordinary recovery path and resumes from its durable seq), then applies the
record through the same replay mutation paths recovery uses. Generations
and cache epochs therefore advance exactly as on the primary, so
plan/cover/result caches invalidate identically.

Role discipline: the local DurabilityManager is marked ``read_only`` — any
direct mutation raises FencedError; only the apply loop (which flips the
manager's ``replaying`` flag around each record, exactly like recovery)
may change state. ``promote()`` lifts the restriction, claims a new
fencing epoch, and turns the node into a primary with its own LogShipper.

Lag accounting: heartbeats carry the primary's last seq;
``replication.lag_seqs`` is how many records behind the apply point is,
``replication.lag_ms`` how long the replica has continuously been behind.
Every heartbeat and ack scores a bounded-staleness check
(``replication.staleness_checks`` / ``.staleness_exceeded``) feeding the
burn-rate SLO registered in obs/slo.py."""

from __future__ import annotations

import os
import shutil
import socket
import threading
import time
from typing import Optional

from geomesa_tpu import config
from geomesa_tpu.durability import faults, rotation
from geomesa_tpu.durability import snapshot as _snap
from geomesa_tpu.durability import wal as _wal
from geomesa_tpu.durability.faults import InjectedCrash
from geomesa_tpu.metrics import REGISTRY as _metrics
from geomesa_tpu.replication import fence as _fence
from geomesa_tpu.replication import protocol as _p


class _Resync(Exception):
    """Drop the connection and reconnect from the durable acked seq (a
    CRC-rejected or out-of-order shipped frame)."""


class Follower:
    """One read replica: local durable store + apply loop."""

    def __init__(self, directory: str, primary_addr,
                 follower_id: Optional[str] = None,
                 params: Optional[dict] = None,
                 connect: bool = True):
        from geomesa_tpu.datastore import TpuDataStore
        self.dir = str(directory)
        self.primary_addr = _p.parse_addr(primary_addr)
        self.id = follower_id or os.path.basename(os.path.abspath(directory))
        self.role = "replica"
        p = {"wal.fsync": "off"}  # the primary's log is authoritative
        p.update(params or {})
        self._params = p
        self.store = TpuDataStore.open(self.dir, params=p)
        self.store.durability.read_only = True
        self.store.replication = self
        self.epoch = _fence.load_epoch(self.dir)
        self.applied_seq = self.store.durability.wal.last_seq
        self.primary_seq = self.applied_seq
        self.dead = False            # a drill-injected "process death"
        self.connected = False
        self.snapshot_installs = 0
        self.crc_rejects = 0
        self.fenced_rejects = 0
        self.applied_records = 0
        self._rows_applied = 0       # local snapshot trigger accounting
        self._caught_up = time.monotonic()
        self._lag_ms = 0.0
        self._acked_seq = 0
        self._stop = threading.Event()
        self._sock: Optional[socket.socket] = None
        self._lock = threading.Lock()
        self._snap_tmp: Optional[str] = None
        self._snap_meta: Optional[dict] = None
        # replication-pipeline telemetry: the newest applied ship stamp
        # (echoed in acks for the primary's e2e timing), the retained
        # apply-trace id riding along as the e2e exemplar, and the
        # traced-apply cadence countdown
        self._last_ship_ts = 0.0
        self._last_apply_trace: Optional[str] = None
        self._until_traced_apply = 1
        from geomesa_tpu import trace as _trace
        _trace.set_node_role("replica")
        _metrics.set_gauge("replication.lag_seqs", lambda: self.lag_seqs)
        _metrics.set_gauge("replication.lag_ms",
                           lambda: round(self.lag_ms, 1))
        self._install_slo()
        self._thread = threading.Thread(target=self._run,
                                        name=f"geomesa-repl-{self.id}",
                                        daemon=True)
        if connect:
            self._thread.start()

    # -- state ---------------------------------------------------------------

    @property
    def lag_seqs(self) -> int:
        return max(0, self.primary_seq - self.applied_seq)

    @property
    def lag_ms(self) -> float:
        """How long this replica has been unable to PROVE freshness.
        ``_caught_up`` advances only when the apply loop demonstrably
        reaches the primary's last seq (an applied frame or a processed
        heartbeat), so a stalled apply loop, a dropped link, or a genuine
        seq backlog all age identically — the router can't be fooled by a
        replica too stuck to notice it is behind. Two heartbeat intervals
        of grace keep a healthy, chatty replica at 0."""
        grace_ms = 2.0 * float(config.REPL_HEARTBEAT_MS.get())
        age_ms = (time.monotonic() - self._caught_up) * 1000.0
        return max(0.0, age_ms - grace_ms)

    def stats(self) -> dict:
        return {"role": self.role,
                "id": self.id,
                "primary": f"{self.primary_addr[0]}:{self.primary_addr[1]}",
                "connected": self.connected,
                "dead": self.dead,
                "epoch": self.epoch,
                "applied_seq": self.applied_seq,
                "acked_seq": self._acked_seq,
                "primary_seq": self.primary_seq,
                "lag_seqs": self.lag_seqs,
                "lag_ms": round(self.lag_ms, 1),
                "staleness_budget_ms": float(config.REPL_STALENESS_MS.get()),
                "applied_records": self.applied_records,
                "snapshot_installs": self.snapshot_installs,
                "crc_rejects": self.crc_rejects,
                "fenced_rejects": self.fenced_rejects}

    def wait_for_seq(self, seq: int, timeout: float = 10.0) -> bool:
        """Block until the apply point reaches ``seq`` (tests/drills)."""
        deadline = time.monotonic() + timeout
        while self.applied_seq < seq:
            if time.monotonic() >= deadline or self.dead:
                return False
            time.sleep(0.005)
        return True

    def _install_slo(self) -> None:
        from geomesa_tpu.obs import slo as _slo
        if not any(o.name == "replication_staleness"
                   for o in _slo.ENGINE.objectives()):
            _slo.ENGINE.add(_slo.replication_objective())

    # -- connection loop ------------------------------------------------------

    def _run(self) -> None:
        backoff_s = float(config.REPL_RECONNECT_MS.get()) / 1000.0
        while not self._stop.is_set():
            sock = None
            try:
                sock = socket.create_connection(self.primary_addr,
                                                timeout=5.0)
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                with self._lock:
                    self._sock = sock
                acked = self.store.durability.wal.last_seq
                _p.send_json(sock, _p.HELLO,
                             {"id": self.id, "acked_seq": acked,
                              "epoch": self.epoch})
                self.connected = True
                self._consume(sock)
            except InjectedCrash:
                # drill semantics: the replica process died mid-apply. The
                # in-flight record is dropped exactly where the crash hit;
                # releasing the file handles here (instead of leaking a
                # zombie whose buffered writes could land later) makes the
                # "restart on the same directory" step well-defined.
                self.dead = True
                self.connected = False
                try:
                    self.store.close()
                except BaseException:
                    pass
                return
            except (_Resync, OSError, _p.ProtocolError):
                _metrics.inc("replication.reconnects")
            except Exception:
                # a flaky-link / injected error mid-apply: reconnect and
                # resume from the durable acked seq like any drop
                _metrics.inc("replication.reconnects")
            finally:
                self.connected = False
                with self._lock:
                    self._sock = None
                if sock is not None:
                    try:
                        sock.close()
                    except OSError:
                        pass
            if not self._stop.is_set():
                self._stop.wait(backoff_s)

    def _consume(self, sock: socket.socket) -> None:
        hb_s = float(config.REPL_HEARTBEAT_MS.get()) / 1000.0
        sock.settimeout(max(1.0, hb_s * 20))
        last_acked = self.store.durability.wal.last_seq
        ack_every = max(1, int(config.REPL_ACK_EVERY.get()))
        while not self._stop.is_set():
            m = _p.recv_msg(sock)
            if m is None:
                return
            mtype, payload = m
            if mtype == _p.FRAME:
                epoch, ship_ts, frame = _p.unpack_frame(payload)
                if not self._epoch_ok(sock, epoch):
                    return
                seq = self._apply_frame(frame, ship_ts=ship_ts)
                if seq is not None and seq - last_acked >= ack_every:
                    self._ack(sock)
                    last_acked = seq
            elif mtype == _p.HEARTBEAT:
                hb = _p.parse_json(payload)
                if not self._epoch_ok(sock, int(hb.get("epoch", 0))):
                    return
                self.primary_seq = max(self.primary_seq,
                                       int(hb.get("last_seq", 0)))
                if self.applied_seq >= self.primary_seq:
                    self._caught_up = time.monotonic()
                self._staleness_tick()
                self._ack(sock)
                last_acked = self.store.durability.wal.last_seq
            elif mtype == _p.SNAP_BEGIN:
                meta = _p.parse_json(payload)
                if not self._epoch_ok(sock, int(meta.get("epoch", 0))):
                    return
                self._snap_begin(meta)
            elif mtype == _p.SNAP_FILE:
                self._snap_file(*_p.unpack_file(payload))
            elif mtype == _p.SNAP_END:
                self._snap_end(_p.parse_json(payload))
                self._ack(sock)
                last_acked = self.store.durability.wal.last_seq
            elif mtype == _p.FENCE:
                # the primary demoted itself mid-session; adopt the epoch
                # it named and wait for a new primary at this address
                self._adopt_epoch(int(_p.parse_json(payload)
                                      .get("epoch", 0)))
                return

    def _epoch_ok(self, sock: socket.socket, epoch: int) -> bool:
        """Enforce the fencing invariant on every primary->follower
        message: a stale epoch is rejected and answered with the higher
        one (never applied — split-brain writes stop here)."""
        if epoch < self.epoch:
            self.fenced_rejects += 1
            _metrics.inc("replication.fenced_rejects")
            try:
                _p.send_json(sock, _p.FENCE, {"epoch": self.epoch})
            except OSError:
                pass
            return False
        if epoch > self.epoch:
            self._adopt_epoch(epoch)
        return True

    def _adopt_epoch(self, epoch: int) -> None:
        if epoch > self.epoch:
            self.epoch = _fence.save_epoch(self.dir, epoch)

    # -- applying -------------------------------------------------------------

    def _apply_frame(self, frame: bytes,
                     ship_ts: float = 0.0) -> Optional[int]:
        """Verify, locally log, then apply one shipped frame; returns its
        seq (None when it was an already-held duplicate). Every
        REPL_TRACE_EVERY-th apply runs under a RETAINED root trace whose
        global id rides the next ack back to the primary as the
        ``repl.e2e`` exemplar — the fleet p99 links to a concrete remote
        apply a reader can pull up."""
        faults.serve_gate("repl.apply")
        from geomesa_tpu import trace as _trace
        try:
            seq, kind_name, payload = _wal.verify_frame(frame)
        except ValueError as e:
            self._reject_crc(str(e))
        wal = self.store.durability.wal
        if seq <= wal.last_seq:
            return None  # duplicate after an ack-lagged resume
        try:
            wal.append_frame(frame)
        except ValueError as e:
            self._reject_crc(str(e))
        traced = False
        every = int(config.REPL_TRACE_EVERY.get())
        if every > 0 and _trace.enabled():
            self._until_traced_apply -= 1
            traced = self._until_traced_apply <= 0
        if traced:
            self._until_traced_apply = every
            with _trace.trace("repl.apply", seq=seq,
                              kind=kind_name) as t:
                if t is not None:
                    t.sampled_hint = True  # pin it in the tail ring
                    self._last_apply_trace = t.global_id
                self._apply_record(kind_name, payload)
        else:
            self._apply_record(kind_name, payload)
        if ship_ts:
            # per-hop ship→apply latency (shared wall clock): the
            # follower half of the replication-pipeline breakdown
            self._last_ship_ts = max(self._last_ship_ts, ship_ts)
            _metrics.observe("repl.ship_to_apply",
                             max(0.0, time.time() - ship_ts))
        self.applied_seq = seq
        self.applied_records += 1
        self._acked_seq = wal.last_seq
        _metrics.inc("replication.applied_records")
        _metrics.inc("replication.applied_bytes", len(frame))
        if self.applied_seq >= self.primary_seq:
            self.primary_seq = self.applied_seq
            self._caught_up = time.monotonic()
        self._maybe_local_snapshot()
        return seq

    def _reject_crc(self, why: str) -> None:
        self.crc_rejects += 1
        _metrics.inc("replication.crc_rejects")
        raise _Resync(f"rejected shipped frame: {why}")

    def _apply_record(self, kind: str, payload: bytes) -> None:
        """Apply through the recovery replay paths with local logging
        suppressed (the shipped frame is already in the local WAL)."""
        from geomesa_tpu.durability.recovery import _apply_record
        mgr = self.store.durability
        mgr.replaying = True
        try:
            _apply_record(self.store, kind, payload)
            if kind in ("append", "upsert"):
                meta = _wal.peek_meta(payload)
                self._rows_applied += int(meta.get("rows", 0)) or 0
        except Exception:
            _metrics.inc("replication.apply_errors")
        finally:
            mgr.replaying = False

    def _maybe_local_snapshot(self) -> None:
        """Bound the replica's own restart-replay horizon: snapshot
        locally on the manager's ordinary thresholds (its row/byte
        accounting is suppressed while replaying, so the follower keeps
        its own)."""
        mgr = self.store.durability
        if self._rows_applied >= mgr._snapshot_rows:
            self._rows_applied = 0
            mgr.snapshot()

    def _staleness_tick(self) -> None:
        self._lag_ms = self.lag_ms
        _metrics.inc("replication.staleness_checks")
        if self._lag_ms > float(config.REPL_STALENESS_MS.get()):
            _metrics.inc("replication.staleness_exceeded")

    def _ack(self, sock: socket.socket) -> None:
        faults.serve_gate("repl.ack")
        wal = self.store.durability.wal
        self._acked_seq = wal.last_seq
        ack = {"id": self.id, "acked_seq": wal.last_seq,
               "applied_seq": self.applied_seq,
               "ts_ms": time.time() * 1000.0}
        if self._last_ship_ts:
            # echo the newest applied ship stamp (+ the retained apply
            # trace, once) so the primary times ship→apply→ack and pins
            # the repl.e2e exemplar to a fetchable remote trace
            ack["ship_ts"] = self._last_ship_ts
            if self._last_apply_trace is not None:
                ack["apply_trace"] = self._last_apply_trace
                self._last_apply_trace = None
        _p.send_json(sock, _p.ACK, ack)
        _metrics.inc("replication.acks_sent")
        self._staleness_tick()

    # -- snapshot catch-up ----------------------------------------------------

    def _snap_begin(self, meta: dict) -> None:
        seq = int(meta["wal_seq"])
        self._snap_meta = meta
        self._snap_tmp = os.path.join(self.dir, f".tmp-snapshot-{seq:020d}")
        shutil.rmtree(self._snap_tmp, ignore_errors=True)
        os.makedirs(self._snap_tmp)

    def _snap_file(self, name: str, data: bytes) -> None:
        if self._snap_tmp is None:
            raise _p.ProtocolError("SNAP_FILE before SNAP_BEGIN")
        with open(os.path.join(self._snap_tmp, name), "wb") as fh:
            fh.write(data)
            rotation.fsync_file(fh)

    def _snap_end(self, meta: dict) -> None:
        """Install the shipped snapshot and restart the local store from
        it: the local WAL and older snapshots are discarded (the shipped
        image supersedes this replica's whole lineage) and shipping
        resumes at wal_seq + 1."""
        from geomesa_tpu.datastore import TpuDataStore
        if self._snap_tmp is None:
            raise _p.ProtocolError("SNAP_END before SNAP_BEGIN")
        seq = int(meta["wal_seq"])
        old = self.store
        old.replication = None
        old.close()
        shutil.rmtree(os.path.join(self.dir, "wal"), ignore_errors=True)
        for _s, p in _snap.snapshot_dirs(self.dir):
            shutil.rmtree(p, ignore_errors=True)
        rotation.atomic_install(
            self._snap_tmp, os.path.join(self.dir, f"snapshot-{seq:020d}"))
        self._snap_tmp = self._snap_meta = None
        self.store = TpuDataStore.open(self.dir, params=self._params)
        self.store.durability.read_only = True
        self.store.replication = self
        self.applied_seq = self.store.durability.wal.last_seq
        self._acked_seq = self.applied_seq
        self.snapshot_installs += 1
        self._rows_applied = 0
        _metrics.inc("replication.snapshot_installs")

    # -- lifecycle ------------------------------------------------------------

    def promote(self, host: str = "127.0.0.1", port: int = 0):
        """Failover: stop following, claim a fresh fencing epoch strictly
        above everything witnessed, lift the read-only fence, and start
        shipping as the new primary. Returns the new LogShipper."""
        from geomesa_tpu.replication.shipper import LogShipper
        self.close(keep_store=True)
        self.store.durability.read_only = False
        self.epoch = _fence.bump_epoch(self.dir, at_least=self.epoch)
        self.role = "promoted"
        self.store.replication = None
        _metrics.inc("replication.promotions")
        return LogShipper(self.store, host=host, port=port)

    def close(self, keep_store: bool = False) -> None:
        self._stop.set()
        with self._lock:
            sock = self._sock
        if sock is not None:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass
        if self._thread.is_alive():
            self._thread.join(timeout=5.0)
        if not keep_store:
            if getattr(self.store, "replication", None) is self:
                self.store.replication = None
            self.store.close()
