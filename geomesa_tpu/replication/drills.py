"""Deterministic fleet fault drills.

≙ the chaos-under-control discipline production storage fleets run (kill a
tablet server mid-compaction, partition a rack, watch nothing break) —
but executed deterministically on the durability/faults.py registry
instead of racing real chaos: each drill builds a miniature fleet under a
scratch directory, injects exactly one failure at a registered point, and
asserts the recovery invariant the architecture promises. The four drills
map 1:1 onto the failure modes the replication design must survive:

  replica_kill   a follower dies mid-ship (InjectedCrash at repl.apply);
                 a restart on the same directory converges to a
                 byte-identical table state with ZERO acknowledged
                 primary writes lost
  lag_spike      a stalled apply loop ages the replica past the bounded-
                 staleness budget; the router demotes it (still serving
                 fresh reads from the primary), then restores it once it
                 catches up
  torn_frame     a shipped frame is corrupted in flight; the follower
                 rejects it on CRC, resynchronizes from its acked seq,
                 and converges with nothing lost or doubled
  partition      two would-be primaries after a split; the fencing epoch
                 makes every stale-epoch write impossible to replicate
                 and demotes the loser the moment the partition heals

Each drill returns a structured report and scores
``drill.<name>.runs`` / ``drill.<name>.passed`` counters (surfaced by
``geomesa-tpu debug replication``); tests assert ``report["ok"]``."""

from __future__ import annotations

import hashlib
import os
import time
from typing import Optional

import numpy as np

from geomesa_tpu import config
from geomesa_tpu.durability import faults
from geomesa_tpu.durability import wal as _wal
from geomesa_tpu.metrics import REGISTRY as _metrics

SPEC = "name:String,v:Int,dtg:Date,*geom:Point"
_DTG0 = int(np.datetime64("2024-01-01T06:00:00", "ms").astype(np.int64))


def make_batch(sft, i: int, n: int = 40):
    """Deterministic feature batch ``i`` (drills and the fleet tests share
    the generator so oracle comparisons are exact)."""
    from geomesa_tpu.features.table import FeatureTable
    rng = np.random.default_rng(1000 + i)
    data = {"name": rng.choice(["a", "b", "c"], n).astype(object),
            "v": (rng.integers(0, 100, n) + i).astype(np.int32),
            "dtg": _DTG0 + rng.integers(0, 3_600_000, n),
            "geom": (rng.uniform(-10, 10, n), rng.uniform(-10, 10, n))}
    return FeatureTable.build(sft, data,
                              fids=[f"b{i}_{j}" for j in range(n)])


def fingerprint(store) -> dict:
    """type -> sha256 of the merged (main ∪ delta) columnar table, using
    the WAL's deterministic codec — byte-identical state, not just equal
    counts."""
    from geomesa_tpu.features.table import FeatureTable
    out = {}
    with store._lock:
        views = {}
        for t in store.get_type_names():
            tbl = store.tables.get(t)
            delta = store.deltas.get(t)
            if tbl is not None and delta is not None:
                tbl = FeatureTable.concat([tbl, delta])
            elif tbl is None:
                tbl = delta
            views[t] = tbl
    for t, tbl in views.items():
        if tbl is None:
            out[t] = "empty"
            continue
        payload = _wal.encode_table({"rows": len(tbl)}, tbl)
        out[t] = hashlib.sha256(payload).hexdigest()
    return out


def fingerprint_dir(path: str) -> dict:
    """Open a (shut-down) node's durability dir read-only, recover its
    state, and fingerprint it. The fleet soak's conservation check runs
    this over every node dir AFTER the subprocesses exit — byte-identical
    fingerprints across primary and followers close the loop that no
    acked write was lost or reordered anywhere in the fleet."""
    from geomesa_tpu.datastore import TpuDataStore
    store = TpuDataStore.open(path, params={"wal.fsync": "off",
                                            "scheduler": False})
    try:
        return fingerprint(store)
    finally:
        store.close()


def _mk_primary(path: str):
    from geomesa_tpu.datastore import TpuDataStore
    from geomesa_tpu.replication.shipper import LogShipper
    store = TpuDataStore.open(path, params={"wal.fsync": "off"})
    store.create_schema("t", SPEC)
    store.load("t", make_batch(store.schemas["t"], 0))
    return store, LogShipper(store)


def _score(name: str, report: dict) -> dict:
    report["name"] = name
    _metrics.inc(f"drill.{name}.runs")
    if report.get("ok"):
        _metrics.inc(f"drill.{name}.passed")
    return report


def _wait(predicate, timeout_s: float = 10.0) -> bool:
    deadline = time.monotonic() + timeout_s
    while not predicate():
        if time.monotonic() >= deadline:
            return False
        time.sleep(0.01)
    return True


# -- the drills ---------------------------------------------------------------


def drill_replica_kill(base_dir: str) -> dict:
    """Kill the follower's apply loop mid-ship, restart it on the same
    directory, and require byte-identical convergence: zero acknowledged
    primary writes lost."""
    from geomesa_tpu.replication.follower import Follower
    faults.reset()
    primary = shipper = f1 = f2 = None
    report: dict = {"ok": False}
    try:
        primary, shipper = _mk_primary(os.path.join(base_dir, "primary"))
        rdir = os.path.join(base_dir, "replica")
        f1 = Follower(rdir, shipper.address, follower_id="r1")
        if not f1.wait_for_seq(primary.durability.wal.last_seq):
            report["error"] = "initial sync never completed"
            return report
        # die on the 2nd applied frame of the incoming burst
        faults.arm_serve_crash("repl.apply", at=2)
        for i in range(1, 5):  # 4 acknowledged batches while the kill arms
            primary.load("t", make_batch(primary.schemas["t"], i))
        primary.remove_features("t", "v < 5")
        acked_seq = primary.durability.wal.last_seq
        report["killed"] = _wait(lambda: f1.dead, 10.0)
        faults.reset()
        f2 = Follower(rdir, shipper.address, follower_id="r1")
        report["converged"] = f2.wait_for_seq(acked_seq, timeout=15.0)
        want, got = fingerprint(primary), fingerprint(f2.store)
        report["fingerprint_equal"] = want == got
        report["acked_seq"] = acked_seq
        report["replica_seq"] = f2.applied_seq
        report["zero_acked_lost"] = f2.applied_seq >= acked_seq and \
            want == got
        report["ok"] = bool(report["killed"] and report["converged"]
                            and report["zero_acked_lost"])
        return report
    finally:
        faults.reset()
        for x in (f1, f2):
            if x is not None:
                try:
                    x.close()
                except Exception:
                    pass
        if primary is not None:
            primary.close()
        _score("replica_kill", report)


def drill_lag_spike(base_dir: str) -> dict:
    """Stall the follower's apply loop past the bounded-staleness budget:
    the router must demote it (reads keep flowing, fresh, from the
    primary) and restore it once it catches up."""
    from geomesa_tpu.replication.follower import Follower
    from geomesa_tpu.serve.router import LocalEndpoint, ReplicaRouter
    faults.reset()
    primary = shipper = f = None
    report: dict = {"ok": False}
    staleness = config.REPL_STALENESS_MS
    old_staleness = staleness._override
    try:
        staleness.set(400.0)
        primary, shipper = _mk_primary(os.path.join(base_dir, "primary"))
        f = Follower(os.path.join(base_dir, "replica"), shipper.address,
                     follower_id="r1")
        f.wait_for_seq(primary.durability.wal.last_seq)
        router = ReplicaRouter([LocalEndpoint("primary", primary),
                                LocalEndpoint("r1", f)])
        router.probe_all(force=True)
        report["healthy_before"] = \
            router.stats()["endpoints"]["r1"]["state"] == "healthy"
        # one apply stalls 1.2s: the whole consume loop (heartbeats
        # included) freezes, so provable freshness ages past the budget
        faults.arm_serve_delay("repl.apply", seconds=1.2, n=1)
        primary.load("t", make_batch(primary.schemas["t"], 1))
        report["demoted_during_spike"] = _wait(
            lambda: (router.probe_all(force=True) or True)
            and router.stats()["endpoints"]["r1"]["state"] == "demoted",
            timeout_s=3.0)
        fresh = primary.count("t")
        routed = router.count("t")  # must come from the primary, fresh
        report["fresh_read_during_spike"] = routed == fresh
        faults.reset()
        report["caught_up"] = f.wait_for_seq(
            primary.durability.wal.last_seq, timeout=10.0)
        report["recovered_healthy"] = _wait(
            lambda: (router.probe_all(force=True) or True)
            and router.stats()["endpoints"]["r1"]["state"] == "healthy",
            timeout_s=5.0)
        report["ok"] = all(report.get(k) for k in
                           ("healthy_before", "demoted_during_spike",
                            "fresh_read_during_spike", "caught_up",
                            "recovered_healthy"))
        return report
    finally:
        faults.reset()
        if old_staleness is None:
            staleness.unset()
        else:
            staleness.set(old_staleness)
        if f is not None:
            f.close()
        if primary is not None:
            primary.close()
        _score("lag_spike", report)


def drill_torn_frame(base_dir: str) -> dict:
    """Corrupt one shipped frame in flight: the follower must reject it
    on CRC, resync from its acked seq, and converge with nothing lost or
    doubled."""
    from geomesa_tpu.replication.follower import Follower
    faults.reset()
    primary = shipper = f = None
    report: dict = {"ok": False}
    try:
        primary, shipper = _mk_primary(os.path.join(base_dir, "primary"))
        f = Follower(os.path.join(base_dir, "replica"), shipper.address,
                     follower_id="r1")
        f.wait_for_seq(primary.durability.wal.last_seq)
        faults.arm_repl_corrupt(1)
        for i in range(1, 3):
            primary.load("t", make_batch(primary.schemas["t"], i))
        report["rejected"] = _wait(lambda: f.crc_rejects >= 1, 10.0)
        report["converged"] = f.wait_for_seq(
            primary.durability.wal.last_seq, timeout=10.0)
        report["fingerprint_equal"] = \
            fingerprint(primary) == fingerprint(f.store)
        report["crc_rejects"] = f.crc_rejects
        report["ok"] = all(report.get(k) for k in
                           ("rejected", "converged", "fingerprint_equal"))
        return report
    finally:
        faults.reset()
        if f is not None:
            f.close()
        if primary is not None:
            primary.close()
        _score("torn_frame", report)


def drill_partition(base_dir: str) -> dict:
    """Split-brain: a partition leaves two would-be primaries. The
    follower that has witnessed the winner's fencing epoch must reject
    every frame the loser ships (frame-level check), which demotes the
    loser — whose subsequent local writes then raise FencedError. No
    write under a stale epoch is ever applied anywhere."""
    from geomesa_tpu.replication.fence import FencedError
    from geomesa_tpu.replication.follower import Follower
    faults.reset()
    a = ship_a = b = c = ship_b = None
    report: dict = {"ok": False}
    try:
        a, ship_a = _mk_primary(os.path.join(base_dir, "a"))
        b = Follower(os.path.join(base_dir, "b"), ship_a.address,
                     follower_id="b")
        c = Follower(os.path.join(base_dir, "c"), ship_a.address,
                     follower_id="c")
        for x in (b, c):
            x.wait_for_seq(a.durability.wal.last_seq)
        base_fids = set(a.tables["t"].fids)
        # PARTITION: b loses sight of a and is promoted (epoch 2)
        ship_b = b.promote()
        b.store.load("t", make_batch(b.store.schemas["t"], 1))  # winner w2
        # c (still attached to a) learns the new epoch — the healed side
        # of the partition hears from the new primary first
        c._adopt_epoch(ship_b.epoch)
        report["epochs"] = {"a": ship_a.epoch, "b": ship_b.epoch,
                            "c": c.epoch}
        # the stale primary keeps writing (it does not know it lost) ...
        a.load("t", make_batch(a.schemas["t"], 2))               # loser w3
        # ... and its shipped frame is rejected at c's epoch check, which
        # fences a the moment the FENCE answer lands
        report["stale_frame_rejected"] = _wait(
            lambda: c.fenced_rejects >= 1, 10.0)
        report["loser_fenced"] = _wait(lambda: ship_a.fenced, 10.0)
        try:
            a.load("t", make_batch(a.schemas["t"], 3))
            report["loser_write_refused"] = False
        except FencedError:
            report["loser_write_refused"] = True
        # no stale-epoch write ever landed on c: its fids are exactly the
        # pre-partition set (it never saw the winner's w2 either — it was
        # attached to the loser — but it must NEVER hold the loser's w3)
        w3_fids = {f"b2_{j}" for j in range(40)}
        c_fids = set() if c.store.tables.get("t") is None \
            else set(c.store.tables["t"].fids) | (
                set(c.store.deltas["t"].fids)
                if c.store.deltas.get("t") is not None else set())
        report["no_stale_write_applied"] = not (w3_fids & c_fids) and \
            c_fids == base_fids
        report["ok"] = all(report.get(k) for k in
                           ("stale_frame_rejected", "loser_fenced",
                            "loser_write_refused",
                            "no_stale_write_applied"))
        return report
    finally:
        faults.reset()
        for x in (c,):
            if x is not None:
                x.close()
        if b is not None:
            b.close(keep_store=True)
            b.store.close()   # closes ship_b (primary role)
        if a is not None:
            a.close()
        _score("partition", report)


DRILLS = {"replica_kill": drill_replica_kill,
          "lag_spike": drill_lag_spike,
          "torn_frame": drill_torn_frame,
          "partition": drill_partition}


def run_all(base_dir: str, only: Optional[list] = None) -> dict:
    """Run every drill (each under its own subdirectory); returns
    name -> report plus a rollup."""
    out = {}
    for name, fn in DRILLS.items():
        if only and name not in only:
            continue
        out[name] = fn(os.path.join(base_dir, name))
    out["ok"] = all(r.get("ok") for k, r in out.items() if k != "ok")
    return out
