"""Replicated serving fleet: primary → follower WAL shipping, read
replicas, fencing epochs, and deterministic fleet fault drills.

≙ the availability layer the reference gets for free from its key-value
backends (HBase/Accumulo/Bigtable replicate regions and fail scans over to
healthy tablet servers — PAPER.md layer map): the CRC-framed,
contiguous-global-seq WAL from durability/ becomes the replication log, a
Follower applies shipped records through the recovery replay paths into
its own durable store, and serve/router.py spreads reads across the fleet
with health-, overload- and lag-aware balancing.

  shipper.LogShipper   primary-side WAL tailing + snapshot catch-up server
  follower.Follower    read replica: verify → local-log → apply → ack
  fence                fencing epochs (split-brain write prevention)
  protocol             the length-prefixed socket transport
  drills               deterministic fleet fault drills (replica kill,
                       lag spike, torn shipped frame, partition fencing)

Cluster v2 composes these primitives per shard: cluster/cells.py scopes
one fencing-epoch directory and one shipper/follower pair to each Morton
key-range cell, so split-brain and failover are contained inside the
cell that lost its primary while the other shards keep serving.
"""

from geomesa_tpu.replication.fence import FencedError  # noqa: F401
from geomesa_tpu.replication.follower import Follower  # noqa: F401
from geomesa_tpu.replication.shipper import LogShipper  # noqa: F401
