"""Length-prefixed replication transport: the wire between primary and
followers.

Deliberately minimal — one TCP socket per follower, full duplex (the
primary's session thread sends, a paired reader thread consumes acks), and
every message is::

    u32 payload_len | u8 type | payload

Control messages (HELLO/HEARTBEAT/ACK/FENCE/SNAP_*) carry JSON payloads;
FRAME carries ``u64 epoch | f64 ship_ts`` followed by the **verbatim
on-disk WAL frame** (``crc|len|seq|kind|payload``) — the shipper forwards
bytes it CRC-verified off disk, and the follower re-verifies the same CRC
on receipt before appending the identical bytes to its own log.
``ship_ts`` (wall-clock seconds at send) is the replication-pipeline
telemetry stamp: the follower scores ship→apply latency against it and
echoes the latest one in its ACKs, so the primary times the full
ship→apply→ack pipeline (the fleet ``repl.e2e`` histogram) without any
clock coordination beyond what the hosts already share. Snapshot catch-up
ships the installed snapshot directory file-by-file (SNAP_FILE payload:
``u16 name_len | name | bytes``).
"""

from __future__ import annotations

import json
import socket
import struct
from typing import Optional, Tuple

_HDR = struct.Struct("<IB")      # payload length, message type
_FRAMEH = struct.Struct("<Qd")   # FRAME prefix: epoch, ship wall-clock s
_NAME = struct.Struct("<H")      # SNAP_FILE name length prefix

# a single message never legitimately exceeds this (largest: one snapshot
# npz); a bigger length prefix means a corrupt/hostile stream
MAX_MSG_BYTES = 1 << 31

HELLO = 1        # follower -> primary: {id, acked_seq, epoch}
FRAME = 2        # primary -> follower: u64 epoch | raw WAL frame
SNAP_BEGIN = 3   # primary -> follower: {wal_seq, epoch, files}
SNAP_FILE = 4    # primary -> follower: u16 name_len | name | bytes
SNAP_END = 5     # primary -> follower: {wal_seq}
HEARTBEAT = 6    # primary -> follower: {last_seq, ts_ms, epoch}
ACK = 7          # follower -> primary: {id, acked_seq, applied_seq, ts_ms}
FENCE = 8        # either direction: {epoch} — sender witnessed a higher
                 # fencing epoch than the peer's; peer must demote

NAMES = {HELLO: "hello", FRAME: "frame", SNAP_BEGIN: "snap_begin",
         SNAP_FILE: "snap_file", SNAP_END: "snap_end",
         HEARTBEAT: "heartbeat", ACK: "ack", FENCE: "fence"}


class ProtocolError(Exception):
    """Malformed message on the replication socket."""


def send_msg(sock: socket.socket, mtype: int, payload: bytes = b"") -> None:
    sock.sendall(_HDR.pack(len(payload), mtype) + payload)


def send_json(sock: socket.socket, mtype: int, obj: dict) -> None:
    send_msg(sock, mtype, json.dumps(obj, separators=(",", ":")).encode())


def recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    """``n`` bytes or None on clean EOF; raises on a mid-message EOF."""
    chunks = []
    got = 0
    while got < n:
        b = sock.recv(min(n - got, 1 << 20))
        if not b:
            if got == 0:
                return None
            raise ProtocolError(f"connection closed mid-message "
                                f"({got}/{n} bytes)")
        chunks.append(b)
        got += len(b)
    return b"".join(chunks)


def recv_msg(sock: socket.socket) -> Optional[Tuple[int, bytes]]:
    """(type, payload) or None on clean EOF."""
    hdr = recv_exact(sock, _HDR.size)
    if hdr is None:
        return None
    length, mtype = _HDR.unpack(hdr)
    if length > MAX_MSG_BYTES:
        raise ProtocolError(f"message length {length} over cap")
    payload = recv_exact(sock, length) if length else b""
    if length and payload is None:
        raise ProtocolError("connection closed before payload")
    return mtype, payload


def parse_json(payload: bytes) -> dict:
    try:
        return json.loads(payload.decode())
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise ProtocolError(f"bad json payload: {e}")


def pack_frame(epoch: int, frame: bytes, ship_ts: float = 0.0) -> bytes:
    return _FRAMEH.pack(epoch, ship_ts) + frame


def unpack_frame(payload: bytes) -> Tuple[int, float, bytes]:
    """(epoch, ship_ts, frame) — ship_ts 0.0 means unstamped."""
    if len(payload) <= _FRAMEH.size:
        raise ProtocolError("short frame message")
    epoch, ship_ts = _FRAMEH.unpack_from(payload)
    return epoch, ship_ts, payload[_FRAMEH.size:]


def pack_file(name: str, data: bytes) -> bytes:
    nb = name.encode()
    return _NAME.pack(len(nb)) + nb + data


def unpack_file(payload: bytes) -> Tuple[str, bytes]:
    if len(payload) < _NAME.size:
        raise ProtocolError("short file message")
    (nlen,) = _NAME.unpack_from(payload)
    name = payload[_NAME.size:_NAME.size + nlen].decode()
    if not name or "/" in name or "\\" in name or ".." in name:
        raise ProtocolError(f"unsafe snapshot file name {name!r}")
    return name, payload[_NAME.size + nlen:]


def parse_addr(addr) -> Tuple[str, int]:
    """'host:port' (or a (host, port) pair) -> (host, port)."""
    if isinstance(addr, (tuple, list)):
        return str(addr[0]), int(addr[1])
    host, _, port = str(addr).rpartition(":")
    if not host or not port.isdigit():
        raise ValueError(f"bad address {addr!r} (want host:port)")
    return host, int(port)
