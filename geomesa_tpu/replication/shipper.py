"""LogShipper: the primary side of the WAL-shipping pipeline.

≙ the replication story the reference inherits from its key-value backends
(HBase region replication / Accumulo table replication both tail the WAL
and ship edits to peers): here the contiguous-global-seq, CRC-framed WAL
(durability/wal.py) IS the replication log. The shipper accepts follower
connections, resumes each one from its acked sequence — falling back to a
snapshot-catchup (reusing the installed incremental snapshots) when the
acked seq was garbage-collected out of the log — and then tails the live
WAL, forwarding frames **verbatim** so the follower re-verifies the same
CRC the primary wrote.

One session thread per follower sends; a paired reader thread consumes
ACKs (per-follower acked/applied seq → the router's promote-by-highest-
acked input) and FENCE messages (a follower that has witnessed a higher
fencing epoch demotes this node: ``fenced`` flips and the
DurabilityManager refuses every subsequent write — split-brain writes are
impossible, see replication/fence.py)."""

from __future__ import annotations

import os
import socket
import threading
import time
from typing import Dict, Optional

from geomesa_tpu import config
from geomesa_tpu.durability import faults
from geomesa_tpu.durability import snapshot as _snap
from geomesa_tpu.durability import wal as _wal
from geomesa_tpu.metrics import REGISTRY as _metrics
from geomesa_tpu.replication import fence as _fence
from geomesa_tpu.replication import protocol as _p

# frames shipped per tail poll before a heartbeat/ack interleave
_SHIP_BATCH = 256


class LogShipper:
    """Primary-side replication endpoint: a TCP server shipping WAL
    frames + snapshot catch-ups to N followers."""

    role = "primary"

    def __init__(self, store, host: str = "127.0.0.1", port: int = 0):
        if getattr(store, "durability", None) is None:
            raise ValueError("replication requires a durable store "
                             "(TpuDataStore.open)")
        self.store = store
        self.dur = store.durability
        self.path = self.dur.path
        self.epoch = _fence.load_epoch(self.path)
        if self.epoch == 0:
            self.epoch = _fence.save_epoch(self.path, 1)
        self.fenced = False
        self.fenced_by: Optional[int] = None
        self._lock = threading.Lock()
        self.followers: Dict[str, dict] = {}
        self._conns: list = []
        self._closed = False
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, int(port)))
        self._srv.listen(16)
        self.host, self.port = self._srv.getsockname()[:2]
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="geomesa-repl-ship", daemon=True)
        self._accept_thread.start()
        store.replication = self
        from geomesa_tpu import trace as _trace
        _trace.set_node_role("primary")
        _metrics.set_gauge("replication.followers",
                           lambda: len([f for f in self.followers.values()
                                        if f.get("connected")]))

    # -- surfaces ------------------------------------------------------------

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def min_acked_seq(self) -> int:
        with self._lock:
            acked = [f["acked_seq"] for f in self.followers.values()
                     if f.get("connected")]
        return min(acked) if acked else 0

    def stats(self) -> dict:
        wal = self.dur.wal
        now = time.monotonic()
        with self._lock:
            followers = {
                fid: {
                    "addr": f.get("addr"),
                    "connected": bool(f.get("connected")),
                    "acked_seq": f["acked_seq"],
                    "applied_seq": f["applied_seq"],
                    "lag_seqs": max(0, wal.last_seq - f["acked_seq"]),
                    "last_ack_age_ms":
                        round((now - f["last_ack"]) * 1000.0, 1)
                        if f.get("last_ack") else None,
                    "snapshots_shipped": f.get("snapshots", 0),
                }
                for fid, f in self.followers.items()}
        return {"role": "fenced" if self.fenced else "primary",
                "epoch": self.epoch,
                "fenced": self.fenced,
                "fenced_by": self.fenced_by,
                "address": self.address,
                "last_seq": wal.last_seq,
                "synced_seq": wal.synced_seq,
                "followers": followers}

    # -- fencing -------------------------------------------------------------

    def _fence_self(self, higher_epoch: int) -> None:
        """A peer witnessed a higher epoch: this node lost primaryship.
        Durably witness the epoch (a restart must not silently reclaim the
        role) and refuse every subsequent write via the manager's fence
        check."""
        with self._lock:
            if self.fenced and (self.fenced_by or 0) >= higher_epoch:
                return
            self.fenced = True
            self.fenced_by = int(higher_epoch)
        _fence.save_epoch(self.path, higher_epoch)
        _metrics.inc("replication.fence_events")

    # -- server --------------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                conn, addr = self._srv.accept()
            except OSError:
                return  # server socket closed
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._lock:
                self._conns.append(conn)
            threading.Thread(target=self._session, args=(conn, addr),
                             name="geomesa-repl-session",
                             daemon=True).start()

    def _session(self, conn: socket.socket, addr) -> None:
        fid = None
        try:
            conn.settimeout(30.0)
            m = _p.recv_msg(conn)
            if m is None or m[0] != _p.HELLO:
                return
            hello = _p.parse_json(m[1])
            fid = str(hello.get("id") or f"{addr[0]}:{addr[1]}")
            acked = int(hello.get("acked_seq", 0))
            their_epoch = int(hello.get("epoch", 0))
            if their_epoch > self.epoch:
                # the connecting node has seen a NEWER primary than us: we
                # are the stale side of a partition — demote immediately
                self._fence_self(their_epoch)
                _p.send_json(conn, _p.FENCE, {"epoch": their_epoch})
                return
            wal = self.dur.wal
            if acked > wal.last_seq:
                # divergent history (the follower outran this log): refuse
                # rather than ship a conflicting lineage
                _metrics.inc("replication.divergent_hellos")
                return
            with self._lock:
                st = self.followers.setdefault(
                    fid, {"acked_seq": acked, "applied_seq": acked,
                          "last_ack": None, "snapshots": 0})
                st["addr"] = f"{addr[0]}:{addr[1]}"
                st["connected"] = True
                st["acked_seq"] = max(st["acked_seq"], acked)
            reader = threading.Thread(target=self._ack_loop,
                                      args=(conn, fid),
                                      name="geomesa-repl-acks", daemon=True)
            reader.start()
            self._ship(conn, fid, acked)
        except (OSError, _p.ProtocolError):
            pass
        finally:
            if fid is not None:
                with self._lock:
                    if fid in self.followers:
                        self.followers[fid]["connected"] = False
            try:
                conn.close()
            except OSError:
                pass
            with self._lock:
                if conn in self._conns:
                    self._conns.remove(conn)

    def _ack_loop(self, conn: socket.socket, fid: str) -> None:
        """Consume follower -> primary traffic for one session."""
        try:
            conn.settimeout(None)
            while not self._closed:
                m = _p.recv_msg(conn)
                if m is None:
                    return
                mtype, payload = m
                if mtype == _p.ACK:
                    ack = _p.parse_json(payload)
                    with self._lock:
                        st = self.followers.get(fid)
                        if st is not None:
                            st["acked_seq"] = max(
                                st["acked_seq"], int(ack.get("acked_seq", 0)))
                            st["applied_seq"] = max(
                                st["applied_seq"],
                                int(ack.get("applied_seq", 0)))
                            st["last_ack"] = time.monotonic()
                    _metrics.inc("replication.acks_received")
                    self._score_pipeline(fid, ack)
                elif mtype == _p.FENCE:
                    self._fence_self(int(_p.parse_json(payload)
                                         .get("epoch", 0)))
                    return
        except (OSError, _p.ProtocolError):
            return

    def _score_pipeline(self, fid: str, ack: dict) -> None:
        """Replication-pipeline telemetry from one ACK: the follower
        echoes the newest ship stamp it applied (``ship_ts``) plus its
        measured apply latency, so the primary observes the full
        ship→apply→ack pipeline on ITS clock pair: ``repl.ship_to_ack``
        (wire + apply + ack wire) and the end-to-end ``repl.e2e`` — the
        histogram the fleet surface reads, exemplar-linked to the
        follower's retained apply trace when one rode along."""
        ship_ts = ack.get("ship_ts")
        if not ship_ts:
            return
        e2e_s = max(0.0, time.time() - float(ship_ts))
        _metrics.observe("repl.ship_to_ack", e2e_s)
        apply_trace = ack.get("apply_trace")
        if apply_trace:
            # fleet p99 -> this exemplar -> the follower's apply trace
            _metrics.observe_exemplar("repl.e2e", e2e_s, str(apply_trace))
        else:
            _metrics.observe("repl.e2e", e2e_s)

    # -- shipping ------------------------------------------------------------

    def _oldest_wal_seq(self) -> Optional[int]:
        segs = _wal.segments(self.dur.wal.dir, self.dur.wal.name)
        return _wal.segment_first_seq(segs[0]) if segs else None

    def _ship(self, conn: socket.socket, fid: str, acked: int) -> None:
        conn.settimeout(None)
        wal = self.dur.wal
        start = acked
        oldest = self._oldest_wal_seq()
        if oldest is not None and acked + 1 < oldest:
            # the follower's resume point was GC'd past: snapshot catch-up
            start = self._ship_snapshot(conn, fid)
            if start is None:
                return
        tailer = _wal.WalTailer(wal.dir, wal.name, after_seq=start)
        hb_s = float(config.REPL_HEARTBEAT_MS.get()) / 1000.0
        sent = start
        while not self._closed:
            if self.fenced:
                _p.send_json(conn, _p.FENCE, {"epoch": self.fenced_by})
                return
            wal.flush_to_os()
            frames = tailer.poll(limit=_SHIP_BATCH)
            for seq, _kind, frame in frames:
                faults.serve_gate("repl.ship.frame")
                frame = faults.repl_corrupt(frame)
                # ship-time stamp: the pipeline-latency anchor the
                # follower scores apply latency against and echoes in acks
                _p.send_msg(conn, _p.FRAME,
                            _p.pack_frame(self.epoch, frame,
                                          ship_ts=time.time()))
                sent = seq
                _metrics.inc("replication.shipped_frames")
                _metrics.inc("replication.shipped_bytes", len(frame))
            if len(frames) == _SHIP_BATCH:
                continue  # still draining a backlog: no idle wait yet
            _p.send_json(conn, _p.HEARTBEAT,
                         {"last_seq": wal.last_seq,
                          "ts_ms": time.time() * 1000.0,
                          "epoch": self.epoch})
            wal.wait_for_seq(sent + 1, timeout=hb_s)

    def _ship_snapshot(self, conn: socket.socket, fid: str) -> Optional[int]:
        """Transfer the newest installed snapshot; returns the WAL seq it
        covers (shipping resumes past it), or None when no snapshot can
        bridge the gap."""
        faults.serve_gate("repl.ship.snapshot")
        snaps = _snap.snapshot_dirs(self.path)
        if not snaps:
            _metrics.inc("replication.catchup_impossible")
            return None
        snap_seq, snap_dir = snaps[-1]
        files = sorted(fn for fn in os.listdir(snap_dir)
                       if fn == "catalog.json" or fn.endswith(".npz"))
        _p.send_json(conn, _p.SNAP_BEGIN,
                     {"wal_seq": snap_seq, "epoch": self.epoch,
                      "files": files})
        for fn in files:
            with open(os.path.join(snap_dir, fn), "rb") as fh:
                _p.send_msg(conn, _p.SNAP_FILE, _p.pack_file(fn, fh.read()))
        _p.send_json(conn, _p.SNAP_END, {"wal_seq": snap_seq})
        with self._lock:
            st = self.followers.get(fid)
            if st is not None:
                st["snapshots"] = st.get("snapshots", 0) + 1
        _metrics.inc("replication.snapshots_shipped")
        return snap_seq

    # -- lifecycle -----------------------------------------------------------

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            self._srv.close()
        except OSError:
            pass
        with self._lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
        if self.store is not None and \
                getattr(self.store, "replication", None) is self:
            self.store.replication = None
