"""Mesh-sharded spatial joins: polygon literals × the sharded point table.

The distributed face of the geometry catalog. A spatial join here is the
``st_contains``/``st_intersects`` point-in-polygon shape: a small set of
polygon literals (the broadcast side) joined against the feature table
(the sharded side, partitioned by contiguous Morton key range across the
PR-15 cluster mesh). Execution follows the cluster scan discipline:

  - each process evaluates ONLY its local shard — the catalog's banded
    device kernels classify certain-in/certain-out in f32 and the f64
    host oracle refines the uncertain sliver, so every local verdict is
    exact (``geom.functions.eval_filter_node``, the same code path the
    filter IR uses);
  - per-polygon hit counts reduce with a psum round (allgather + sum —
    counted in ``cluster.psum_rounds`` and the collective telemetry,
    same ledger as ClusterScan's count);
  - pair selects (polygon → matching fids) cannot psum (ragged): each
    process compacts its local matches in index key order and the
    results merge host-side in RANK order. Rank order == Morton key
    order (contiguous key-range partitioning), so concatenation IS the
    global sort order — no re-sort, no k-way heap.

The single-process oracle is the identical code path under an inactive
runtime (one code path, two cardinalities), which is what makes the
2-process CPU dryrun's byte-equality check meaningful rather than
merely probable.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from geomesa_tpu.cluster.runtime import ClusterRuntime, note_collective
from geomesa_tpu.features import geometry as geo
from geomesa_tpu.filter import ir

JOIN_OPS = ("st_contains", "st_intersects")


@dataclass
class JoinResult:
    """Global join verdict — identical on every rank (the equality unit)."""

    op: str
    polygons: int
    counts: List[int]                      # per-polygon global hit counts
    pairs: List[List[str]]                 # per-polygon fids, global key order
    rows_local: int                        # this process's shard size
    rows_global: int                       # psum of shard sizes
    num_processes: int
    wall_s: float
    truncated: bool = False                # pairs capped at max_pairs
    meta: dict = field(default_factory=dict)

    def stable(self) -> dict:
        """The rank-invariant portion: identical on every rank AND on the
        single-process oracle — the dryrun's byte-equality surface."""
        return {
            "op": self.op, "polygons": self.polygons,
            "counts": [int(c) for c in self.counts],
            "pairs": [[str(f) for f in p] for p in self.pairs],
            "rows_global": int(self.rows_global),
            "truncated": bool(self.truncated),
        }

    def to_dict(self) -> dict:
        return {
            **self.stable(),
            "rows_local": int(self.rows_local),
            "num_processes": int(self.num_processes),
            "wall_s": round(float(self.wall_s), 3),
        }


def _literal(poly) -> tuple:
    """Accept WKT strings or parsed ``(code, data)`` literals."""
    lit = geo.parse_wkt(poly) if isinstance(poly, str) else poly
    if lit[0] not in (geo.POLYGON, geo.MULTIPOLYGON):
        raise ValueError(f"spatial join literal must be polygonal: {poly!r}")
    return lit


def _join_node(op: str, lit: tuple, attr: str) -> ir.Filter:
    """The filter-IR node one join probe evaluates — the SAME node shape
    the CQL parser produces for ``st_contains(POLYGON(..), geom)``, so
    join probes and filter queries share kernels, caches and parity."""
    if op == "st_contains":
        return ir.Func("st_contains", (lit, attr))
    if op == "st_intersects":
        return ir.Func("st_intersects", (attr, lit))
    raise ValueError(f"unsupported join op {op!r} (want one of {JOIN_OPS})")


def _psum_counts(rt: Optional[ClusterRuntime],
                 local: np.ndarray) -> np.ndarray:
    """psum a small int64 vector across the cluster (allgather + sum over
    the process axis). Inactive runtimes return the input — callers never
    branch, which is exactly what keeps the oracle on the same path."""
    if rt is None or not rt.active():
        return local
    import jax.numpy as jnp
    from jax.experimental import multihost_utils

    t0 = time.perf_counter()
    out = np.asarray(multihost_utils.process_allgather(jnp.asarray(local)))
    out = out.reshape(rt.num_processes, -1).sum(axis=0)
    rt.note_psum_round()
    note_collective("psum", time.perf_counter() - t0,
                    payload_bytes=int(local.nbytes) * rt.num_processes)
    return out.astype(np.int64)


def _merge_pairs(rt: Optional[ClusterRuntime],
                 local: List[List[str]]) -> List[List[str]]:
    """Rank-order merge of per-polygon fid lists (ragged → exchange)."""
    if rt is None or not rt.active():
        return local
    peers = rt.exchange({"pairs": local}, op="row_exchange")
    return [[fid for p in peers for fid in p["pairs"][j]]
            for j in range(len(local))]


def _key_order(planner) -> np.ndarray:
    """Local rows in primary index key order — the order whose rank-wise
    concatenation is the global key order (z3 when present, mirroring the
    partitioner's Morton coarsening; first index otherwise)."""
    idx = next((i for i in planner.indexes if i.name == "z3"),
               planner.indexes[0])
    return np.asarray(idx.perm, dtype=np.int64)


def local_matches(planner, polygons: Sequence, op: str = "st_contains",
                  rows: Optional[np.ndarray] = None,
                  ) -> Tuple[np.ndarray, np.ndarray]:
    """Shard-local probe: evaluate every polygon against the local table.

    Returns ``(counts, hits)`` — ``counts`` (P,) int64 local hit counts,
    ``hits`` (P, n_local) bool match matrix over ``rows`` (default: the
    primary index's key order, so downstream compaction is already in
    global-mergeable order). Kernel/oracle choice follows the
    ``GEOMESA_TPU_GEOM_KERNELS`` knob via ``eval_filter_node``."""
    from geomesa_tpu.geom.functions import eval_filter_node

    attr = planner.sft.geometry_attribute.name
    if rows is None:
        rows = _key_order(planner)
    nodes = [_join_node(op, _literal(p), attr) for p in polygons]
    hits = np.zeros((len(nodes), len(rows)), dtype=bool)
    for j, node in enumerate(nodes):
        hits[j] = eval_filter_node(node, planner.table, rows)
    return hits.sum(axis=1).astype(np.int64), hits


def spatial_join(planner, polygons: Sequence, op: str = "st_contains",
                 runtime: Optional[ClusterRuntime] = None,
                 fids: Optional[np.ndarray] = None,
                 rows: Optional[np.ndarray] = None,
                 with_pairs: bool = True,
                 max_pairs: Optional[int] = None) -> JoinResult:
    """Distributed ``op(polygon, geom)`` join against the sharded table.

    ``planner`` serves this process's LOCAL shard (on an inactive runtime:
    the whole table — the oracle). ``fids``/``rows`` default to the primary
    index's key order; pass the pair-select payload explicitly when the
    caller already holds it (the dryrun's ``fids_sorted``).

    ``max_pairs`` caps each polygon's pair list AFTER the rank-order merge
    (a global prefix in key order — deterministic, so capped results still
    compare byte-equal across cardinalities)."""
    t0 = time.perf_counter()
    if rows is None:
        rows = _key_order(planner)
    if fids is None:
        fids = np.asarray(planner.table.fids)[rows]
    counts_l, hits = local_matches(planner, polygons, op, rows=rows)

    sizes = _psum_counts(runtime, np.asarray(
        [len(rows)] + list(counts_l), dtype=np.int64))
    rows_global, counts = int(sizes[0]), [int(c) for c in sizes[1:]]

    pairs: List[List[str]] = []
    truncated = False
    if with_pairs:
        local_pairs = [[str(f) for f in np.asarray(fids)[hits[j]]]
                       for j in range(len(hits))]
        pairs = _merge_pairs(runtime, local_pairs)
        if max_pairs is not None:
            truncated = any(len(p) > max_pairs for p in pairs)
            pairs = [p[:max_pairs] for p in pairs]

    nproc = runtime.num_processes if runtime is not None \
        and runtime.active() else 1
    return JoinResult(
        op=op, polygons=len(hits), counts=counts, pairs=pairs,
        rows_local=int(len(rows)), rows_global=rows_global,
        num_processes=nproc, wall_s=time.perf_counter() - t0,
        truncated=truncated)


def func_counts(planner, queries: Sequence[str],
                runtime: Optional[ClusterRuntime] = None) -> Dict[str, int]:
    """st_* function COUNT queries over the sharded table: each shard
    evaluates its local rows through the planner's geometry-kernel refine
    (banded device classify + f64 host refine of the uncertain sliver),
    and the per-query counts psum-reduce. The device-only cluster count
    path cannot host-refine Func residuals, so function queries reduce
    here instead — one psum round for the whole battery."""
    from geomesa_tpu.filter.parser import parse_ecql

    rows = _key_order(planner)
    local = np.asarray(
        [int(planner._refine_mask(parse_ecql(q), rows).sum())
         for q in queries], dtype=np.int64)
    tot = _psum_counts(runtime, local)
    return {q: int(c) for q, c in zip(queries, tot)}


def join_battery(planner, polygons: Sequence,
                 runtime: Optional[ClusterRuntime] = None,
                 fids: Optional[np.ndarray] = None,
                 max_pairs: Optional[int] = None) -> dict:
    """Both join ops over one polygon set — the dryrun/bench unit.
    ``stable`` is identical on every rank (the orchestrator asserts it
    against the single-process oracle verbatim); ``meta`` carries the
    rank-local timings/sizes, excluded from equality."""
    out: dict = {"stable": {}, "meta": {}}
    for op in JOIN_OPS:
        r = spatial_join(planner, polygons, op, runtime=runtime,
                         fids=fids, max_pairs=max_pairs)
        out["stable"][op] = r.stable()
        out["meta"][op] = {"rows_local": int(r.rows_local),
                           "num_processes": int(r.num_processes),
                           "wall_s": round(float(r.wall_s), 3)}
    return out
