"""Binding layer: filter-IR Func nodes → the geometry catalog.

Evaluates `ir.Func` / `ir.FuncCmp` predicates and `ir.FuncExpr` projections
over a FeatureTable. Two backends share one argument-evaluation core:

* host — the exact f64 oracle (`geom.oracle`); this is what
  `filter/evaluate.py` dispatches to, so it stays THE parity reference.
* kernels — the vmapped device catalog (`geom.catalog`) for the staged
  production refine path (`GEOMESA_TPU_GEOM_KERNELS`); boolean predicates
  stay exact (banded + host-refined), scalars carry the documented bounds.

Arguments evaluate to `GeomBatch`es — (GeometryArray, idx) pairs — so nested
geometry-valued calls (st_buffer/st_centroid/st_convexHull) compose with
every predicate and with select/export projections (`st_centroid(geom) AS
c`).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Tuple

import numpy as np

from geomesa_tpu import config
from geomesa_tpu.features import geometry as geo
from geomesa_tpu.filter import geom_numpy as gn
from geomesa_tpu.filter import ir
from geomesa_tpu.geom import catalog, oracle


@dataclass
class GeomBatch:
    """A per-row geometry value: ``arr[idx[k]]`` is row k's geometry."""
    arr: geo.GeometryArray
    idx: np.ndarray
    constant: bool            # one shared geometry broadcast to every row
    attr: Optional[str] = None   # set when this is the raw geometry column

    def literal(self) -> tuple:
        """The shared (type_code, data) literal of a constant batch."""
        return self.arr.shape(int(self.idx[0]) if len(self.idx) else 0)


def _rows_of(table, rows: Optional[np.ndarray]) -> np.ndarray:
    if rows is None:
        return np.arange(len(table), dtype=np.int64)
    return np.asarray(rows, dtype=np.int64)


def geom_arg(table, rows: Optional[np.ndarray], arg) -> GeomBatch:
    """Evaluate one function argument to a GeomBatch."""
    r = _rows_of(table, rows)
    if isinstance(arg, str):
        col = table.column(arg)
        if not isinstance(col, geo.GeometryArray):
            raise TypeError(f"Attribute {arg} is not a geometry")
        return GeomBatch(col, r, False, arg)
    if isinstance(arg, ir.FuncExpr):
        return eval_funcexpr(table, rows, arg)
    if isinstance(arg, tuple) and len(arg) == 2 and isinstance(arg[0], int):
        lit = geo.GeometryArray.from_shapes([arg])
        return GeomBatch(lit, np.zeros(len(r), dtype=np.int64), True)
    raise TypeError(f"Bad geometry argument {arg!r}")


def eval_funcexpr(table, rows: Optional[np.ndarray],
                  e: ir.FuncExpr) -> GeomBatch:
    """st_buffer / st_centroid / st_convexHull → a new GeomBatch (host f64,
    collapsing constant inputs to a single computed geometry)."""
    g = geom_arg(table, rows, e.args[0])
    idx = np.zeros(1, dtype=np.int64) if g.constant else g.idx
    if e.name == "st_centroid":
        cx, cy = oracle.centroid(g.arr, idx)
        out = geo.GeometryArray.points(cx, cy)
    elif e.name == "st_convexhull":
        out = geo.GeometryArray.from_shapes(
            oracle.convex_hull_shapes(g.arr, idx))
    elif e.name == "st_buffer":
        if len(e.args) < 2 or not isinstance(e.args[1], float):
            raise TypeError("st_buffer needs a numeric distance")
        out = geo.GeometryArray.from_shapes(
            oracle.buffer_shapes(g.arr, idx, float(e.args[1])))
    else:
        raise TypeError(f"{e.name} is not geometry-valued")
    if g.constant:
        n = len(g.idx)
        return GeomBatch(out, np.zeros(n, dtype=np.int64), True)
    return GeomBatch(out, np.arange(len(idx), dtype=np.int64), False)


def _two_args(table, rows, args, name: str) -> Tuple[GeomBatch, GeomBatch]:
    if len(args) != 2:
        raise TypeError(f"{name} takes 2 geometry arguments")
    return geom_arg(table, rows, args[0]), geom_arg(table, rows, args[1])


def _pairwise_shapes(b: GeomBatch) -> List[tuple]:
    return [b.arr.shape(int(i)) for i in b.idx]


def scalar_values(table, rows: Optional[np.ndarray], name: str,
                  args: tuple, kernels: bool = False) -> np.ndarray:
    """f64 values of a scalar st_* call at ``rows``."""
    if name in ("st_area", "st_length"):
        g = geom_arg(table, rows, args[0])
        idx = np.zeros(1, dtype=np.int64) if g.constant else g.idx
        if kernels:
            v = catalog.unary_values(g.arr, idx)[
                "area" if name == "st_area" else "length"]
        else:
            fn = oracle.area if name == "st_area" else oracle.length
            v = fn(g.arr, idx)
        return np.broadcast_to(v, (len(g.idx),)).copy() if g.constant else v
    if name == "st_distance":
        a, b = _two_args(table, rows, args, name)
        if a.constant and not b.constant:
            a, b = b, a
        if b.constant:
            lit = b.literal()
            if kernels:
                return catalog.batch_distance(a.arr, a.idx, lit)
            return oracle.distance(a.arr, a.idx, lit)
        # both sides row-dependent: exact per-row host loop
        return np.asarray(
            [gn.geometry_distance(a.arr, int(a.idx[k]), shp)
             for k, shp in enumerate(_pairwise_shapes(b))],
            dtype=np.float64)
    raise TypeError(f"{name} is not a scalar function")


def bool_values(table, rows: Optional[np.ndarray], name: str,
                args: tuple, kernels: bool = False) -> np.ndarray:
    """Exact boolean values of st_contains / st_intersects at ``rows``."""
    a, b = _two_args(table, rows, args, name)
    if name == "st_intersects":
        if a.constant and not b.constant:
            a, b = b, a
        if b.constant:
            lit = b.literal()
            if kernels:
                return catalog.batch_predicate(a.arr, a.idx,
                                               "intersects", lit)
            return oracle.intersects(a.arr, a.idx, lit)
        return np.asarray(
            [gn.geometry_intersects(a.arr, int(a.idx[k]), shp)
             for k, shp in enumerate(_pairwise_shapes(b))], dtype=bool)
    if name == "st_contains":
        # st_contains(a, b): a contains b
        if a.constant:
            lit = a.literal()
            if kernels:
                return catalog.batch_predicate(b.arr, b.idx, "within", lit)
            return oracle.contains_literal(b.arr, b.idx, lit)
        if b.constant:
            lit = b.literal()
            if kernels:
                return catalog.batch_predicate(a.arr, a.idx,
                                               "contains", lit)
            return oracle.feature_contains(a.arr, a.idx, lit)
        return np.concatenate(
            [oracle.feature_contains(a.arr, a.idx[k: k + 1], shp)
             for k, shp in enumerate(_pairwise_shapes(b))]) \
            if len(a.idx) else np.zeros(0, dtype=bool)
    raise TypeError(f"{name} is not a boolean predicate")


def _prefilter_box(f) -> Optional[Tuple[str, float, float, float, float]]:
    """(attr, xmin, ymin, xmax, ymax) bbox prefilter for a Func/FuncCmp on
    the raw geometry column vs a constant literal, or None. Sound: every
    matching feature's bbox overlaps the box."""
    if isinstance(f, ir.Func):
        args = f.args
        attr = lit = None
        for a in args:
            if isinstance(a, str):
                attr = a
            elif isinstance(a, tuple):
                lit = a
        if attr is None or lit is None or len(args) != 2:
            return None
        x0, y0, x1, y1 = gn.literal_bbox(lit)
        return attr, x0, y0, x1, y1
    if isinstance(f, ir.FuncCmp) and f.name == "st_distance" \
            and f.op in ("<", "<="):
        attr = lit = None
        for a in f.args:
            if isinstance(a, str):
                attr = a
            elif isinstance(a, tuple):
                lit = a
        if attr is None or lit is None or len(f.args) != 2:
            return None
        d = max(float(f.value), 0.0)
        x0, y0, x1, y1 = gn.literal_bbox(lit)
        return attr, x0 - d, y0 - d, x1 + d, y1 + d
    return None


def eval_filter_node(f, table, rows: Optional[np.ndarray],
                     kernels: Optional[bool] = None) -> np.ndarray:
    """Boolean mask at ``rows`` for an ir.Func / ir.FuncCmp node, with a
    bbox prefilter for the common attr-vs-literal shapes. ``kernels`` None
    reads GEOMESA_TPU_GEOM_KERNELS; filter/evaluate.py passes False (it IS
    the host oracle)."""
    if kernels is None:
        kernels = bool(config.GEOM_KERNELS.get())
    r = _rows_of(table, rows)
    pre = _prefilter_box(f)
    sub = None
    if pre is not None:
        attr, x0, y0, x1, y1 = pre
        col = table.column(attr)
        if isinstance(col, geo.GeometryArray):
            bb = col.bboxes()[r]
            cand = np.nonzero((bb[:, 0] <= x1) & (bb[:, 2] >= x0)
                              & (bb[:, 1] <= y1) & (bb[:, 3] >= y0))[0]
            out = np.zeros(len(r), dtype=bool)
            if len(cand) == 0:
                return out
            sub = r[cand]
    eval_rows = r if sub is None else sub
    if isinstance(f, ir.Func):
        vals = bool_values(table, eval_rows, f.name, f.args, kernels)
    else:
        from geomesa_tpu.filter.evaluate import _apply_op
        s = scalar_values(table, eval_rows, f.name, f.args, kernels)
        vals = _apply_op(f.op, s, f.value)
    if sub is None:
        return vals
    out = np.zeros(len(r), dtype=bool)
    out[cand] = vals
    return out


# -- projections (select / export: "st_centroid(geom) AS c") -----------------


def parse_projection(spec: str):
    """Parse one ``st_fn(args) AS name`` projection term → (FuncExpr-or-
    (name, args), alias). Plain attribute names pass through as (attr,
    alias)."""
    from geomesa_tpu.filter.parser import _Tokens, _parse_func_args
    text = spec.strip()
    toks = _Tokens(text)
    tok = toks.peek()
    if tok is None:
        raise ValueError("Empty projection")
    k, v = tok
    if k != "word":
        raise ValueError(f"Bad projection {spec!r}")
    name = v.lower()
    if name in ir.FUNC_NAMES:
        toks.next()
        args = _parse_func_args(toks)
        node = (name, args)
    else:
        toks.next()
        node = v
    alias = None
    if toks.peek_word() == "AS":
        toks.next()
        alias = toks.expect("word")
    if toks.peek() is not None:
        raise ValueError(f"Trailing input in projection {spec!r}")
    if alias is None:
        alias = name if isinstance(node, tuple) else v
    return node, alias


def project_values(table, rows: Optional[np.ndarray], node,
                   kernels: Optional[bool] = None):
    """Evaluate a parsed projection term at ``rows``.

    Returns (kind, values): kind 'scalar' → f64 array; kind 'geom' → list of
    (type_code, data) shapes; kind 'attr' → the raw column values.
    """
    if kernels is None:
        kernels = bool(config.GEOM_KERNELS.get())
    r = _rows_of(table, rows)
    if isinstance(node, str):
        col = table.column(node)
        if isinstance(col, geo.GeometryArray):
            return "geom", [col.shape(int(i)) for i in r]
        from geomesa_tpu.features.table import StringColumn
        if isinstance(col, StringColumn):
            return "attr", [col.vocab[c] for c in col.codes[r]]
        return "attr", np.asarray(col)[r]
    name, args = node
    if name in ir.FUNC_SCALAR:
        return "scalar", scalar_values(table, r, name, args, kernels)
    if name in ir.FUNC_BOOLEAN:
        return "scalar", bool_values(table, r, name, args,
                                     kernels).astype(np.float64)
    e = ir.FuncExpr(name, args)
    if name == "st_centroid" and kernels:
        g = geom_arg(table, r, args[0])
        if not g.constant:
            u = catalog.unary_values(g.arr, g.idx)
            return "geom", [(geo.POINT, [float(x), float(y)])
                            for x, y in zip(u["cx"], u["cy"])]
    b = eval_funcexpr(table, r, e)
    return "geom", _pairwise_shapes(b)


def parse_projections(spec: str) -> List[tuple]:
    """Split a comma-separated projection list on TOP-LEVEL commas only
    (``st_distance(geom, POINT(1 2)) AS d, val`` is two terms, not three)
    and parse each — the ``?select=`` / ``--select`` surface grammar."""
    terms, depth, start = [], 0, 0
    for i, ch in enumerate(spec):
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
        elif ch == "," and depth == 0:
            terms.append(spec[start:i])
            start = i + 1
    terms.append(spec[start:])
    return [parse_projection(t) for t in terms if t.strip()]


def projection_columns(table, rows: Optional[np.ndarray], spec: str,
                       kernels: Optional[bool] = None) -> dict:
    """Evaluate a ``?select=`` projection list → ordered {alias: values}
    with JSON-safe values: geometry terms serialize to WKT, scalars to
    floats, raw attributes to native types. Shared by the REST features
    route and the CLI export path."""
    out: dict = {}
    for node, alias in parse_projections(spec):
        kind, vals = project_values(table, rows, node, kernels)
        if kind == "geom":
            out[alias] = [geo.write_wkt(*s) for s in vals]
        elif kind == "scalar":
            out[alias] = [float(v) for v in np.asarray(vals)]
        else:
            out[alias] = [v.item() if isinstance(v, np.generic) else v
                          for v in vals]
    return out
