"""Exact numpy host oracles for the geometry function catalog.

≙ the JTS operations behind the reference's geomesa-spark-jts UDFs
(st_area/st_length/st_centroid/st_distance/st_buffer/st_convexHull/
st_contains/st_intersects). Every catalog device kernel in
``geom.catalog`` is judged against these f64 implementations; the filter
evaluator (``filter.evaluate``) and the fused program's uncertain-sliver
refine call them directly, so the oracle IS the semantics.

Semantics notes (documented in the README function table):

* ``st_area``  — planar shoelace: Σ per polygon part of |shell| − Σ|holes|,
  in squared degrees; 0 for points and lines.
* ``st_length`` — Σ boundary segment lengths (JTS ``getLength``: line length
  for lineal features, ring perimeter for polygonal ones, 0 for points).
* ``st_centroid`` — JTS discipline: area-weighted for polygonal features
  with nonzero area, else length-weighted over boundary segments, else the
  vertex mean.
* ``st_buffer`` — vertex-offset approximation: the convex hull of the
  feature's vertices Minkowski-summed with a regular octagon of circumradius
  ``d / cos(π/8)``. A guaranteed superset of the true d-buffer of the hull
  whose boundary overshoots by ≤ ``d·(sec(π/8) − 1) ≈ 0.0824·d``; the
  envelope (bbox ± d) is exact.
* ``st_convexHull`` — Andrew monotone chain, strict (collinear boundary
  vertices dropped), CCW vertex order starting from the lexicographic min.
* ``st_contains(a, b)`` — boundary-inclusive containment (matches the
  existing ``ir.Contains``/``batch_within`` discipline).
* ``st_distance`` — exact min distance in degrees (0 when intersecting).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from geomesa_tpu.features import geometry as geo
from geomesa_tpu.filter import geom_batch as gb
from geomesa_tpu.filter import geom_numpy as gn

# Minkowski octagon: circumradius d/cos(pi/8) circumscribes the d-disk, so
# the octagonal buffer CONTAINS the true buffer; max overshoot sec(pi/8)-1.
BUFFER_SEC = float(1.0 / np.cos(np.pi / 8.0))
BUFFER_OVERSHOOT = BUFFER_SEC - 1.0   # ≈ 0.082392
_OCT_ANGLES = (np.arange(8) + 0.5) * (np.pi / 4.0)


def octagon_offsets(d: float) -> np.ndarray:
    """(8, 2) f64 vertex offsets of the buffer octagon (d=0 → zeros)."""
    r = float(d) * BUFFER_SEC
    return np.stack([r * np.cos(_OCT_ANGLES), r * np.sin(_OCT_ANGLES)],
                    axis=1)


def feature_shape(arr: geo.GeometryArray, i: int) -> tuple:
    """(type_code, nested lists) literal of feature ``i``."""
    return arr.shape(int(i))


def _ring_signed_area(pts: np.ndarray) -> float:
    """Signed shoelace area of one (closed or unclosed) ring, f64."""
    x, y = pts[:, 0], pts[:, 1]
    return 0.5 * float(np.sum(x * np.roll(y, -1) - np.roll(x, -1) * y))


def _feature_rings(arr: geo.GeometryArray, i: int
                   ) -> List[Tuple[np.ndarray, bool]]:
    """[(ring coords, is_shell)] for feature ``i`` (polygonal only)."""
    out = []
    g0, g1 = int(arr.geom_offsets[i]), int(arr.geom_offsets[i + 1])
    for p in range(g0, g1):
        r0, r1 = int(arr.part_offsets[p]), int(arr.part_offsets[p + 1])
        for r in range(r0, r1):
            c0, c1 = int(arr.ring_offsets[r]), int(arr.ring_offsets[r + 1])
            out.append((arr.coords[c0:c1], r == r0))
    return out


def area(arr: geo.GeometryArray, rows: np.ndarray) -> np.ndarray:
    """(len(rows),) f64 planar areas."""
    rows = np.asarray(rows, dtype=np.int64)
    out = np.zeros(len(rows), dtype=np.float64)
    polyish = (geo.POLYGON, geo.MULTIPOLYGON)
    for k, i in enumerate(rows):
        if int(arr.type_codes[i]) not in polyish:
            continue
        a = 0.0
        for ring, is_shell in _feature_rings(arr, int(i)):
            ra = abs(_ring_signed_area(ring))
            a += ra if is_shell else -ra
        out[k] = max(a, 0.0)
    return out


def length(arr: geo.GeometryArray, rows: np.ndarray) -> np.ndarray:
    """(len(rows),) f64 boundary lengths (perimeter for polygons)."""
    rows = np.asarray(rows, dtype=np.int64)
    if len(rows) == 0:
        return np.zeros(0, dtype=np.float64)
    segs, fid = gb.build_segments(arr, rows)
    if len(segs) == 0:
        return np.zeros(len(rows), dtype=np.float64)
    ln = np.hypot(segs[:, 2] - segs[:, 0], segs[:, 3] - segs[:, 1])
    return np.bincount(fid, weights=ln, minlength=len(rows))


# areal-centroid gate: a feature routes through the area-weighted moment
# formula only when |2·area| exceeds this fraction of its bbox extent² —
# below it the f32 kernel's moment/area quotient is ill-conditioned, so BOTH
# the oracle and the kernel (which reads the host-computed mode flag) fall
# back to the length-weighted boundary centroid. Shared rule == shared
# semantics; the deviation from JTS (thin slivers centroid their boundary)
# is documented in the README.
AREAL_REL = 1e-3

MODE_POINT, MODE_LINEAL, MODE_AREAL = 0, 1, 2


def centroid_mode(arr: geo.GeometryArray, i: int) -> int:
    """Shared areal/lineal/point cascade decision (host f64)."""
    i = int(i)
    code = int(arr.type_codes[i])
    if code in (geo.POLYGON, geo.MULTIPOLYGON):
        a2 = 0.0
        for ring, is_shell in _feature_rings(arr, i):
            sa = _ring_signed_area(ring)
            a2 += (1.0 if is_shell else -1.0) * 2.0 * abs(sa)
        bb = arr.bboxes()[i]
        ext2 = max((bb[2] - bb[0]) * (bb[3] - bb[1]), 1e-300)
        if abs(a2) > AREAL_REL * ext2:
            return MODE_AREAL
    if code != geo.POINT and len(gn.feature_segments(arr, i)):
        return MODE_LINEAL
    return MODE_POINT


def centroid(arr: geo.GeometryArray, rows: np.ndarray
             ) -> Tuple[np.ndarray, np.ndarray]:
    """((C,) x, (C,) y) f64 JTS-style centroids (cascade per
    ``centroid_mode``)."""
    rows = np.asarray(rows, dtype=np.int64)
    cx = np.zeros(len(rows), dtype=np.float64)
    cy = np.zeros(len(rows), dtype=np.float64)
    for k, i in enumerate(rows):
        i = int(i)
        pts = arr.feature_coords(i)
        # local origin: keeps the shoelace moments well-conditioned (the
        # kernel shifts identically, so parity is apples-to-apples)
        ox, oy = float(np.mean(pts[:, 0])), float(np.mean(pts[:, 1]))
        mode = centroid_mode(arr, i)
        if mode == MODE_AREAL:
            a2 = 0.0
            mx = my = 0.0
            for ring, is_shell in _feature_rings(arr, i):
                x = ring[:, 0] - ox
                y = ring[:, 1] - oy
                x2, y2 = np.roll(x, -1), np.roll(y, -1)
                cross = x * y2 - x2 * y
                sa = 0.5 * float(np.sum(cross))
                sgn = 1.0 if is_shell else -1.0
                w = sgn * (1.0 if sa >= 0 else -1.0)
                a2 += w * 2.0 * sa
                mx += w * float(np.sum((x + x2) * cross))
                my += w * float(np.sum((y + y2) * cross))
            if abs(a2) > 0.0:
                cx[k] = ox + mx / (3.0 * a2)
                cy[k] = oy + my / (3.0 * a2)
                continue
            mode = MODE_LINEAL
        if mode == MODE_LINEAL:
            segs = gn.feature_segments(arr, i)
            ln = np.hypot(segs[:, 2] - segs[:, 0], segs[:, 3] - segs[:, 1])
            tot = float(np.sum(ln))
            if tot > 0.0:
                cx[k] = float(np.sum(ln * (segs[:, 0] + segs[:, 2]))) \
                    / (2.0 * tot)
                cy[k] = float(np.sum(ln * (segs[:, 1] + segs[:, 3]))) \
                    / (2.0 * tot)
                continue
        cx[k], cy[k] = ox, oy
    return cx, cy


def distance(arr: geo.GeometryArray, rows: np.ndarray,
             literal: tuple) -> np.ndarray:
    """(len(rows),) f64 exact min distances to the literal geometry."""
    rows = np.asarray(rows, dtype=np.int64)
    if len(rows) == 0:
        return np.zeros(0, dtype=np.float64)
    return gb.batch_distance(arr, rows, literal)


def convex_hull(pts: np.ndarray) -> np.ndarray:
    """Strict convex hull (Andrew monotone chain), CCW from the
    lexicographic-min vertex. Degenerate inputs (≤2 distinct, collinear)
    return the distinct extreme points."""
    pts = np.unique(np.asarray(pts, dtype=np.float64), axis=0)
    if len(pts) <= 2:
        return pts
    # lexicographic sort (x, then y) — np.unique already provides it
    def half(seq):
        h: List[np.ndarray] = []
        for p in seq:
            while len(h) >= 2:
                u, v = h[-1] - h[-2], p - h[-2]
                if u[0] * v[1] - u[1] * v[0] <= 0:
                    h.pop()
                else:
                    break
            h.append(p)
        return h
    lower = half(pts)
    upper = half(pts[::-1])
    hull = np.asarray(lower[:-1] + upper[:-1])
    if len(hull) < 3:   # fully collinear input
        return np.asarray([pts[0], pts[-1]])
    return hull


def convex_hull_of(arr: geo.GeometryArray, i: int) -> np.ndarray:
    return convex_hull(arr.feature_coords(int(i)))


def convex_hull_shapes(arr: geo.GeometryArray,
                       rows: np.ndarray) -> List[tuple]:
    """Hulls as geometry literals (polygon / linestring / point)."""
    out = []
    for i in np.asarray(rows, dtype=np.int64):
        h = convex_hull_of(arr, int(i))
        if len(h) >= 3:
            out.append((geo.POLYGON, [h.tolist() + [h[0].tolist()]]))
        elif len(h) == 2:
            out.append((geo.LINESTRING, h.tolist()))
        else:
            out.append((geo.POINT, h[0].tolist()))
    return out


def buffer_shapes(arr: geo.GeometryArray, rows: np.ndarray,
                  d: float) -> List[tuple]:
    """Octagonal vertex-offset buffers as POLYGON literals (see module
    docstring for the documented error bound)."""
    offs = octagon_offsets(d)
    out = []
    for i in np.asarray(rows, dtype=np.int64):
        pts = arr.feature_coords(int(i))
        swept = (pts[:, None, :] + offs[None, :, :]).reshape(-1, 2)
        h = convex_hull(swept)
        if len(h) >= 3:
            out.append((geo.POLYGON, [h.tolist() + [h[0].tolist()]]))
        elif len(h) == 2:
            out.append((geo.LINESTRING, h.tolist()))
        else:
            out.append((geo.POINT, h[0].tolist()))
    return out


def buffer_envelopes(arr: geo.GeometryArray, rows: np.ndarray,
                     d: float) -> np.ndarray:
    """(C, 4) exact expanded envelopes [xmin ymin xmax ymax] — the
    envelope-exact half of st_buffer."""
    rows = np.asarray(rows, dtype=np.int64)
    bb = arr.bboxes()[rows].astype(np.float64).copy()
    bb[:, 0] -= d
    bb[:, 1] -= d
    bb[:, 2] += d
    bb[:, 3] += d
    return bb


def intersects(arr: geo.GeometryArray, rows: np.ndarray,
               literal: tuple) -> np.ndarray:
    """(len(rows),) bool — feature ∩ literal ≠ ∅ (symmetric)."""
    rows = np.asarray(rows, dtype=np.int64)
    if len(rows) == 0:
        return np.zeros(0, dtype=bool)
    return gb.batch_intersects(arr, rows, literal)


def contains_literal(arr: geo.GeometryArray, rows: np.ndarray,
                     literal: tuple) -> np.ndarray:
    """literal CONTAINS feature (boundary-inclusive) — the
    ``st_contains(LITERAL, geom)`` direction.

    Non-polygonal literals: point literals contain only coincident point
    features; lineal literals contain features whose vertices AND segment
    midpoints all lie on the literal (exact for points, a documented
    sampling approximation for collinear line-on-line cases)."""
    rows = np.asarray(rows, dtype=np.int64)
    if len(rows) == 0:
        return np.zeros(0, dtype=bool)
    lcode = literal[0]
    if lcode in (geo.POLYGON, geo.MULTIPOLYGON):
        return gb.batch_within(arr, rows, literal)
    out = np.zeros(len(rows), dtype=bool)
    lc = gn.literal_coords(literal)
    lsegs = gn.literal_segments(literal)
    for k, i in enumerate(rows):
        i = int(i)
        if int(arr.type_codes[i]) in (geo.POLYGON, geo.MULTIPOLYGON):
            continue
        fc = arr.feature_coords(i)
        if lcode in (geo.POINT, geo.MULTIPOINT):
            match = ((fc[:, None, 0] == lc[None, :, 0])
                     & (fc[:, None, 1] == lc[None, :, 1]))
            out[k] = bool(len(fc)) and bool(match.any(axis=1).all())
            continue
        samples = [fc]
        fsegs = gn.feature_segments(arr, i)
        if len(fsegs):
            samples.append(np.stack(
                [(fsegs[:, 0] + fsegs[:, 2]) * 0.5,
                 (fsegs[:, 1] + fsegs[:, 3]) * 0.5], axis=1))
        pts = np.concatenate(samples)
        out[k] = bool(np.all(gn._points_on_segments(
            pts[:, 0], pts[:, 1], lsegs)))
    return out


def feature_contains(arr: geo.GeometryArray, rows: np.ndarray,
                     literal: tuple) -> np.ndarray:
    """feature CONTAINS literal (boundary-inclusive) — the
    ``st_contains(geom, LITERAL)`` direction. Polygonal features can contain
    anything; lineal/point features contain only geometries lying on them
    (supported for point literals; other degenerate shapes refine per-row).
    """
    rows = np.asarray(rows, dtype=np.int64)
    out = np.zeros(len(rows), dtype=bool)
    if len(rows) == 0:
        return out
    lcode = literal[0]
    if lcode == geo.POINT:
        px, py = float(literal[1][0]), float(literal[1][1])
        for k, i in enumerate(rows):
            i = int(i)
            code = int(arr.type_codes[i])
            if code in (geo.POLYGON, geo.MULTIPOLYGON):
                segs = gn.feature_segments(arr, i)
                out[k] = _point_in_rings(px, py, segs)
            else:
                segs = gn.feature_segments(arr, i)
                if len(segs):
                    out[k] = bool(gn.point_segment_distance(
                        np.asarray([px]), np.asarray([py]), segs)[0] == 0.0)
                else:
                    c0 = int(arr.ring_offsets[arr.part_offsets[
                        arr.geom_offsets[i]]])
                    out[k] = (arr.coords[c0, 0] == px
                              and arr.coords[c0, 1] == py)
        return out
    # general literal: feature must be polygonal; contained iff every
    # literal vertex is in the feature and no boundaries properly cross
    lc = gn.literal_coords(literal)
    lsegs = gn.literal_segments(literal)
    for k, i in enumerate(rows):
        i = int(i)
        if int(arr.type_codes[i]) not in (geo.POLYGON, geo.MULTIPOLYGON):
            continue
        fsegs = gn.feature_segments(arr, i)
        if not all(_point_in_rings(float(x), float(y), fsegs)
                   for x, y in lc):
            continue
        out[k] = not gn._segments_properly_cross(lsegs, fsegs)
    return out


def _point_in_rings(px: float, py: float, segs: np.ndarray) -> bool:
    """Boundary-inclusive point-in-polygon against a segment soup (crossing
    parity; on-edge counts as inside)."""
    if len(segs) == 0:
        return False
    d = gn.point_segment_distance(np.asarray([px]), np.asarray([py]), segs)
    if d[0] == 0.0:
        return True
    x1, y1, x2, y2 = segs[:, 0], segs[:, 1], segs[:, 2], segs[:, 3]
    cond = (y1 > py) != (y2 > py)
    with np.errstate(divide="ignore", invalid="ignore"):
        xs = x1 + (py - y1) * (x2 - x1) / (y2 - y1)
    return bool(np.sum(cond & (xs > px)) % 2 == 1)
