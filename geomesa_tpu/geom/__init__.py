"""Geometry function catalog (≙ geomesa-spark-jts).

`oracle` — exact f64 numpy semantics for every st_* function.
`catalog` — vmapped JAX device kernels + banded-predicate refine.
`join` — mesh-sharded st_contains/st_intersects point-in-polygon joins.
`functions` — the name → implementation registry the filter IR binds to.
"""
