"""Vmapped JAX kernels for the geometry function catalog.

≙ geomesa-spark-jts: the st_* UDF surface, evaluated on-device over the
columnar geometry table instead of per-row on executors. Features are packed
into pow²-padded vertex/segment tables (`pack_features`) and every function
is one jitted, vmapped program over the batch:

  st_area / st_length / st_centroid  — one fused "unary" kernel
  st_distance                        — banded min over segment pairs
  st_contains / st_intersects        — certainty-banded (cin, cout) masks,
                                       uncertain sliver refined by the f64
                                       host oracle → booleans strictly exact
  st_convexHull / st_buffer          — gift-wrap hull (buffer = hull of the
                                       8-offset octagon sweep)

Precision discipline (same as `index/scan.py`): device arithmetic is f32.
Vertices are shifted per-feature to a grid-quantized local origin (multiples
of 1/256 deg — exactly representable in f32, so the in-kernel literal shift
adds no rounding beyond the literal's own f32 cast, ≤ `_IN_DELTA`). Boolean
predicates use the `_pip_band`/`_segpair_band` certainty bands and are exact
after refine; scalar kernels carry the documented forward-error bounds
computed per-feature by `parity_report`.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from geomesa_tpu import config
from geomesa_tpu.features import geometry as geo
from geomesa_tpu.filter import geom_numpy as gn
from geomesa_tpu.geom import oracle
from geomesa_tpu.index.scan import ScanKernels, _pip_band, _segpair_band

_EDGE_PAD_ROW = ScanKernels._EDGE_PAD

# f32 eps and the |f64−f32| lon/lat coordinate bound — shared constants with
# the scan-layer bands (values asserted against scan.py in tests)
_EPS32 = 1.2e-7
_DELTA = 2.5e-5

# certain-miss distance band for predicates: true distance 0 can read at most
# ~4·_DELTA on device, so anything beyond this is certainly disjoint
_MISS_BAND = np.float32(1.5e-4)

# per-op uncertain-sliver / host-refine counters (observability + tests)
STATS: Dict[str, int] = {
    "predicate_calls": 0, "predicate_rows": 0, "refined_rows": 0,
    "unary_calls": 0, "distance_calls": 0, "hull_calls": 0,
    "hull_host_fallbacks": 0,
}
_LOCK = threading.Lock()


def _pow2(n: int, lo: int = 1) -> int:
    p = lo
    while p < n:
        p <<= 1
    return p


# -- feature packing ---------------------------------------------------------


@dataclass
class FeaturePack:
    """Pow²-padded per-feature vertex/segment tables (see module doc)."""
    n: int                  # real feature count (≤ B)
    verts: jnp.ndarray      # (B, K, 2) f32, local-origin shifted
    vmask: jnp.ndarray      # (B, K) bool
    segs: jnp.ndarray       # (B, S, 4) f32, shifted, rings closed
    smask: jnp.ndarray      # (B, S) bool
    wsign: jnp.ndarray      # (B, S) f32 shoelace weights (0 off polygons)
    mode: jnp.ndarray       # (B,) int32 centroid cascade (oracle rule)
    poly: jnp.ndarray       # (B,) bool polygonal feature
    ref: np.ndarray         # (B, 2) f64 local origins (f32-exact values)
    ref32: jnp.ndarray      # (B, 2) f32


def _quantize_ref(bb: np.ndarray) -> np.ndarray:
    """(B, 2) grid-quantized bbox centers, exactly representable in f32."""
    c = np.stack([(bb[:, 0] + bb[:, 2]) * 0.5, (bb[:, 1] + bb[:, 3]) * 0.5],
                 axis=1)
    return np.round(c * 256.0) / 256.0


def pack_features(arr: geo.GeometryArray, rows: np.ndarray) -> FeaturePack:
    rows = np.asarray(rows, dtype=np.int64)
    n = len(rows)
    B = _pow2(max(n, 1), 8)
    bb = arr.bboxes()[rows].astype(np.float64) if n else np.zeros((0, 4))
    ref = np.zeros((B, 2), dtype=np.float64)
    if n:
        ref[:n] = _quantize_ref(bb)
    codes = arr.type_codes[rows] if n else np.zeros(0, dtype=np.int8)
    poly = np.zeros(B, dtype=bool)
    mode = np.zeros(B, dtype=np.int32)
    if n and bool(np.all(codes == geo.POINT)):
        # vectorized fast path: the dominant corpus shape (Z2/Z3 point sfts)
        ci = arr.ring_offsets[arr.part_offsets[arr.geom_offsets[rows]]]
        verts = np.zeros((B, 1, 2), dtype=np.float32)
        verts[:n, 0] = (arr.coords[ci] - ref[:n]).astype(np.float32)
        vmask = np.zeros((B, 1), dtype=bool)
        vmask[:n, 0] = True
        segs = np.tile(_EDGE_PAD_ROW, (B, 1, 1)).astype(np.float32)
        smask = np.zeros((B, 1), dtype=bool)
        wsign = np.zeros((B, 1), dtype=np.float32)
    else:
        vlists, slists, wlists = [], [], []
        K = S = 1
        for k in range(n):
            i = int(rows[k])
            pts = arr.feature_coords(i) - ref[k]
            vlists.append(pts)
            K = max(K, len(pts))
            code = int(codes[k])
            fsegs = gn.feature_segments(arr, i)
            w = np.zeros(len(fsegs), dtype=np.float64)
            if code in (geo.POLYGON, geo.MULTIPOLYGON):
                poly[k] = True
                # per-ring shoelace weight: +1 shells, −1 holes, ×
                # orientation sign (== the oracle's w)
                ws, off = [], 0
                for ring, is_shell in oracle._feature_rings(arr, i):
                    nseg = len(ring) - 1 if np.array_equal(
                        ring[0], ring[-1]) else len(ring)
                    sa = oracle._ring_signed_area(ring)
                    sgn = (1.0 if is_shell else -1.0) \
                        * (1.0 if sa >= 0 else -1.0)
                    ws.append(np.full(nseg, sgn))
                    off += nseg
                if ws:
                    w = np.concatenate(ws)
            if len(fsegs):
                slists.append(fsegs - np.concatenate([ref[k], ref[k]]))
                wlists.append(w)
                S = max(S, len(fsegs))
            else:
                slists.append(np.zeros((0, 4)))
                wlists.append(w)
            mode[k] = oracle.centroid_mode(arr, i)
        K, S = _pow2(K), _pow2(S)
        verts = np.zeros((B, K, 2), dtype=np.float32)
        vmask = np.zeros((B, K), dtype=bool)
        segs = np.tile(_EDGE_PAD_ROW, (B, S, 1)).astype(np.float32)
        smask = np.zeros((B, S), dtype=bool)
        wsign = np.zeros((B, S), dtype=np.float32)
        for k in range(n):
            v, s, w = vlists[k], slists[k], wlists[k]
            verts[k, : len(v)] = v
            vmask[k, : len(v)] = True
            segs[k, : len(s)] = s
            smask[k, : len(s)] = True
            wsign[k, : len(w)] = w
    return FeaturePack(
        n=n, verts=jnp.asarray(verts), vmask=jnp.asarray(vmask),
        segs=jnp.asarray(segs), smask=jnp.asarray(smask),
        wsign=jnp.asarray(wsign), mode=jnp.asarray(mode),
        poly=jnp.asarray(poly), ref=ref,
        ref32=jnp.asarray(ref.astype(np.float32)))


def pack_literal(literal: tuple) -> Tuple[jnp.ndarray, jnp.ndarray, bool]:
    """((L, 4) padded f32 edges, (P, 2) f32 points, polygonal?) in the
    global frame (kernels shift by each feature's ref)."""
    lsegs = gn.literal_segments(literal)
    L = _pow2(max(len(lsegs), 1))
    ls = np.tile(_EDGE_PAD_ROW, (L, 1)).astype(np.float32)
    ls[: len(lsegs)] = lsegs.astype(np.float32)
    lc = gn.literal_coords(literal).astype(np.float32)
    P = _pow2(max(len(lc), 1))
    lp = np.full((P, 2), 3e9, dtype=np.float32)
    lp[: len(lc)] = lc
    return jnp.asarray(ls), jnp.asarray(lp), \
        literal[0] in (geo.POLYGON, geo.MULTIPOLYGON)


# -- kernels -----------------------------------------------------------------


def _pt_seg_d2(px, py, s):
    """Squared point-to-segment distance, broadcasting."""
    x1, y1, x2, y2 = s[..., 0], s[..., 1], s[..., 2], s[..., 3]
    dx, dy = x2 - x1, y2 - y1
    ll = dx * dx + dy * dy
    t = jnp.clip(((px - x1) * dx + (py - y1) * dy)
                 / jnp.where(ll == 0, 1, ll), 0.0, 1.0)
    cx, cy = x1 + t * dx, y1 + t * dy
    return (px - cx) ** 2 + (py - cy) ** 2


def _pip_plain(px, py, e, evalid=None):
    """Unbanded crossing-parity point-in-polygon (distance paths only)."""
    x1, y1, x2, y2 = e[..., 0], e[..., 1], e[..., 2], e[..., 3]
    cond = (y1 > py) != (y2 > py)
    xs = x1 + (py - y1) * (x2 - x1) / jnp.where(y2 == y1, 1.0, y2 - y1)
    cr = cond & (xs > px)
    if evalid is not None:
        cr = cr & evalid
    return (jnp.sum(cr, axis=-1) % 2) == 1


def _cross_plain(a, b):
    """Any proper segment crossing between (..., S, 4) and (..., L, 4)."""
    ax1, ay1, ax2, ay2 = (a[..., :, None, i] for i in range(4))
    bx1, by1, bx2, by2 = (b[..., None, :, i] for i in range(4))

    def orient(ox, oy, px, py, qx, qy):
        return (px - ox) * (qy - oy) - (py - oy) * (qx - ox)

    d1 = orient(ax1, ay1, ax2, ay2, bx1, by1)
    d2 = orient(ax1, ay1, ax2, ay2, bx2, by2)
    d3 = orient(bx1, by1, bx2, by2, ax1, ay1)
    d4 = orient(bx1, by1, bx2, by2, ax2, ay2)
    return (d1 * d2 < 0) & (d3 * d4 < 0)


def _unary_one(verts, vmask, segs, smask, wsign, mode):
    """(area, length, cx, cy) of one packed feature (local frame)."""
    x1, y1, x2, y2 = segs[:, 0], segs[:, 1], segs[:, 2], segs[:, 3]
    sm = smask.astype(jnp.float32)
    cross = (x1 * y2 - x2 * y1) * wsign
    a2 = jnp.sum(cross)
    area = jnp.maximum(a2 * 0.5, 0.0)
    ln = jnp.hypot(x2 - x1, y2 - y1) * sm
    length = jnp.sum(ln)
    # areal moments
    mx = jnp.sum((x1 + x2) * cross)
    my = jnp.sum((y1 + y2) * cross)
    safe_a2 = jnp.where(a2 == 0, 1.0, a2)
    acx, acy = mx / (3.0 * safe_a2), my / (3.0 * safe_a2)
    # lineal: length-weighted midpoints
    tot = jnp.where(length == 0, 1.0, length)
    lcx = jnp.sum(ln * (x1 + x2)) / (2.0 * tot)
    lcy = jnp.sum(ln * (y1 + y2)) / (2.0 * tot)
    # point: vertex mean
    vm = vmask.astype(jnp.float32)
    nv = jnp.maximum(jnp.sum(vm), 1.0)
    pcx = jnp.sum(verts[:, 0] * vm) / nv
    pcy = jnp.sum(verts[:, 1] * vm) / nv
    cx = jnp.where(mode == 2, acx, jnp.where(mode == 1, lcx, pcx))
    cy = jnp.where(mode == 2, acy, jnp.where(mode == 1, lcy, pcy))
    return area, length, cx, cy


_unary_batch = jax.jit(jax.vmap(_unary_one))


def _dist_one(verts, vmask, segs, smask, poly, ref, lsegs, lpts, lit_poly):
    lofs = jnp.concatenate([ref, ref])
    le = lsegs - lofs
    lp = lpts - ref
    vx = jnp.where(vmask, verts[:, 0], 3e9)
    vy = jnp.where(vmask, verts[:, 1], 3e9)
    big = jnp.float32(9e18)
    d2a = jnp.min(jnp.where(vmask[:, None],
                            _pt_seg_d2(vx[:, None], vy[:, None], le[None]),
                            big))
    d2b = jnp.min(jnp.where(smask[None, :],
                            _pt_seg_d2(lp[:, 0][:, None], lp[:, 1][:, None],
                                       segs[None]), big))
    d2c = jnp.min(jnp.where(vmask[:, None],
                            (vx[:, None] - lp[None, :, 0]) ** 2
                            + (vy[:, None] - lp[None, :, 1]) ** 2, big))
    d2 = jnp.minimum(jnp.minimum(d2a, d2b), d2c)
    zero = jnp.any(_cross_plain(jnp.where(smask[:, None], segs, 4e9),
                                le))
    if lit_poly:
        zero |= jnp.any(_pip_plain(vx[:, None], vy[:, None], le[None])
                        & vmask)
    zero |= poly & jnp.any(
        _pip_plain(lp[:, 0][:, None], lp[:, 1][:, None], segs[None],
                   evalid=smask[None, :]))
    return jnp.where(zero, 0.0, jnp.sqrt(d2))


_dist_batch = jax.jit(jax.vmap(_dist_one, in_axes=(0, 0, 0, 0, 0, 0,
                                                   None, None, None)),
                      static_argnums=(8,))


def _pred_one(verts, vmask, segs, smask, poly, ref, lsegs, lpts,
              op, lit_poly, lit_ext):
    """Banded (certainly-true, certainly-false) for one feature.

    op: 0 = intersects, 1 = within (literal ⊇ feature),
    2 = contains (feature ⊇ literal). Everything neither certain-true nor
    certain-false goes to the f64 host oracle.
    """
    lofs = jnp.concatenate([ref, ref])
    le = lsegs - lofs
    lp = lpts - ref
    vx = jnp.where(vmask, verts[:, 0], 3e9)
    vy = jnp.where(vmask, verts[:, 1], 3e9)
    # banded pip: feature verts vs literal edges (pad edges never cross)
    vin, vout = _pip_band(vx[:, None], vy[:, None],
                          le[None, :, 0], le[None, :, 1],
                          le[None, :, 2], le[None, :, 3])
    # banded pip: literal points vs feature edges
    pin, pout = _pip_band(lp[:, 0][:, None], lp[:, 1][:, None],
                          segs[None, :, 0], segs[None, :, 1],
                          segs[None, :, 2], segs[None, :, 3],
                          evalid=smask[None, :])
    # banded segment pairs (S, L)
    si, sm = _segpair_band(
        segs[:, None, 0], segs[:, None, 1], segs[:, None, 2],
        segs[:, None, 3], le[None, :, 0], le[None, :, 1],
        le[None, :, 2], le[None, :, 3])
    si = si & smask[:, None]
    sm = sm | ~smask[:, None]
    # certain-miss distance: true distance can't be 0 beyond the band
    big = jnp.float32(9e18)
    d2a = jnp.min(jnp.where(vmask[:, None],
                            _pt_seg_d2(vx[:, None], vy[:, None], le[None]),
                            big))
    d2b = jnp.min(jnp.where(smask[None, :],
                            _pt_seg_d2(lp[:, 0][:, None], lp[:, 1][:, None],
                                       segs[None]), big))
    d2c = jnp.min(jnp.where(vmask[:, None],
                            (vx[:, None] - lp[None, :, 0]) ** 2
                            + (vy[:, None] - lp[None, :, 1]) ** 2, big))
    far = jnp.minimum(jnp.minimum(d2a, d2b), d2c) > _MISS_BAND * _MISS_BAND
    has_v = jnp.any(vmask)
    if op == 0:
        cin = jnp.any(si)
        if lit_poly:
            cin |= jnp.any(vin & vmask)
        cin |= poly & jnp.any(pin)
        cout = far
    elif op == 1:
        cout = far
        if lit_poly:
            cin = has_v & jnp.all(vin | ~vmask) & jnp.all(sm)
            cout |= jnp.any(vout & vmask)
        else:
            cin = jnp.bool_(False)
    else:
        cout = far | (poly & jnp.any(pout))
        if lit_ext:
            cout |= ~poly
        cin = poly & jnp.all(pin) & jnp.all(sm)
    return cin, cout


_pred_batch = jax.jit(jax.vmap(_pred_one, in_axes=(0, 0, 0, 0, 0, 0,
                                                   None, None, None,
                                                   None, None)),
                      static_argnums=(8, 9, 10))


def _hull_one(verts, vmask):
    """Gift-wrap convex hull of one padded vertex set.

    Returns ((K, 2) hull verts CCW from the lexicographic min, count,
    closed?) — `closed` False (wrap didn't return to start within K steps,
    possible under f32 collinearity ties) → host fallback.
    """
    K = verts.shape[0]
    big = jnp.float32(3e9)
    vx = jnp.where(vmask, verts[:, 0], big)
    vy = jnp.where(vmask, verts[:, 1], big)
    minx = jnp.min(vx)
    start = jnp.argmin(jnp.where(vx == minx, vy, big))

    def pick_next(cur):
        cx, cy = vx[cur], vy[cur]

        def scan_r(r, q):
            qx, qy = vx[q], vy[q]
            rx, ry = vx[r], vy[r]
            cr = (qx - cx) * (ry - cy) - (qy - cy) * (rx - cx)
            d2q = (qx - cx) ** 2 + (qy - cy) ** 2
            d2r = (rx - cx) ** 2 + (ry - cy) ** 2
            valid = vmask[r] & (r != cur)
            better = valid & ((cr < 0) | (q == cur)
                              | ((cr == 0) & (d2r > d2q)))
            return jnp.where(better, r, q)

        return jax.lax.fori_loop(0, K, scan_r, cur)

    def body(k, st):
        cur, out, cnt, done = st
        nxt = pick_next(cur)
        # close on COORDS, not index, so duplicate start points still wrap
        closing = ((vx[nxt] == vx[start]) & (vy[nxt] == vy[start])) \
            | (nxt == cur)
        write = ~done & ~closing
        out = out.at[k].set(jnp.where(write,
                                      jnp.stack([vx[nxt], vy[nxt]]),
                                      out[k]))
        cnt = jnp.where(write, cnt + 1, cnt)
        return nxt, out, cnt, done | closing

    steps = min(K, 160)   # hull sizes beyond this fall back to the host
    out0 = jnp.zeros((K, 2), dtype=jnp.float32)
    out0 = out0.at[0].set(jnp.stack([vx[start], vy[start]]))
    cur, out, cnt, done = jax.lax.fori_loop(
        1, steps + 1, body, (start, out0, jnp.int32(1), jnp.bool_(False)))
    return out, cnt, done


_hull_batch = jax.jit(jax.vmap(_hull_one))


# -- batch entry points ------------------------------------------------------


def _row_chunks(rows: np.ndarray, lit_items: int):
    """Split a row batch so the (B, S, L) pair tables stay under the
    GEOM_CHUNK element budget (S estimated at 64)."""
    budget = max(int(config.GEOM_CHUNK.get()), 1024)
    per = max(1, budget // max(1, 64 * lit_items))
    for s in range(0, len(rows), per):
        yield rows[s: s + per]


def unary_values(arr: geo.GeometryArray, rows: np.ndarray) -> Dict[str, np.ndarray]:
    """{'area', 'length', 'cx', 'cy'} f64 arrays via the fused unary kernel
    (centroids un-shifted back into the global frame in f64)."""
    rows = np.asarray(rows, dtype=np.int64)
    with _LOCK:
        STATS["unary_calls"] += 1
    if len(rows) == 0:
        z = np.zeros(0, dtype=np.float64)
        return {"area": z, "length": z.copy(), "cx": z.copy(),
                "cy": z.copy()}
    p = pack_features(arr, rows)
    area, length, cx, cy = (np.asarray(a) for a in _unary_batch(
        p.verts, p.vmask, p.segs, p.smask, p.wsign, p.mode))
    n = p.n
    return {
        "area": area[:n].astype(np.float64),
        "length": length[:n].astype(np.float64),
        "cx": cx[:n].astype(np.float64) + p.ref[:n, 0],
        "cy": cy[:n].astype(np.float64) + p.ref[:n, 1],
    }


def batch_distance(arr: geo.GeometryArray, rows: np.ndarray,
                   literal: tuple) -> np.ndarray:
    """(len(rows),) f64 kernel distances (documented tol: ≤ 1e-4 + 1e-5·d
    vs the exact oracle — boundary-sliver rows read ≤ band instead of 0)."""
    rows = np.asarray(rows, dtype=np.int64)
    with _LOCK:
        STATS["distance_calls"] += 1
    if len(rows) == 0:
        return np.zeros(0, dtype=np.float64)
    ls, lp, lit_poly = pack_literal(literal)
    parts = []
    for sub in _row_chunks(rows, ls.shape[0] + lp.shape[0]):
        p = pack_features(arr, sub)
        d = np.asarray(_dist_batch(p.verts, p.vmask, p.segs, p.smask,
                                   p.poly, p.ref32, ls, lp, lit_poly))
        parts.append(d[: p.n].astype(np.float64))
    return np.concatenate(parts)


_OP_CODE = {"intersects": 0, "within": 1, "contains": 2}


def batch_predicate(arr: geo.GeometryArray, rows: np.ndarray, op: str,
                    literal: tuple) -> np.ndarray:
    """Exact boolean predicate batch: banded device kernel + f64 host-oracle
    refine of the uncertain sliver.

    op: 'intersects' (symmetric), 'within' (literal contains feature),
    'contains' (feature contains literal). Boundary-inclusive throughout.
    """
    rows = np.asarray(rows, dtype=np.int64)
    if len(rows) == 0:
        return np.zeros(0, dtype=bool)
    code = _OP_CODE[op]
    ls, lp, lit_poly = pack_literal(literal)
    lit_ext = literal[0] not in (geo.POINT, geo.MULTIPOINT)
    cins, couts = [], []
    for sub in _row_chunks(rows, ls.shape[0] + lp.shape[0]):
        p = pack_features(arr, sub)
        ci, co = _pred_batch(p.verts, p.vmask, p.segs, p.smask, p.poly,
                             p.ref32, ls, lp, code, lit_poly, lit_ext)
        cins.append(np.asarray(ci)[: p.n])
        couts.append(np.asarray(co)[: p.n])
    cin = np.concatenate(cins)
    cout = np.concatenate(couts)
    out = cin.copy()
    unc = ~cin & ~cout
    nunc = int(np.count_nonzero(unc))
    with _LOCK:
        STATS["predicate_calls"] += 1
        STATS["predicate_rows"] += len(rows)
        STATS["refined_rows"] += nunc
    if nunc:
        sub = rows[unc]
        if op == "intersects":
            out[unc] = oracle.intersects(arr, sub, literal)
        elif op == "within":
            out[unc] = oracle.contains_literal(arr, sub, literal)
        else:
            out[unc] = oracle.feature_contains(arr, sub, literal)
    return out


def kernel_hulls(arr: geo.GeometryArray, rows: np.ndarray):
    """[(H_i, 2) f64 hull vertex arrays] via the gift-wrap kernel, falling
    back to the host oracle for unclosed wraps (f32 collinearity ties)."""
    rows = np.asarray(rows, dtype=np.int64)
    with _LOCK:
        STATS["hull_calls"] += 1
    if len(rows) == 0:
        return []
    p = pack_features(arr, rows)
    hv, cnt, ok = (np.asarray(a) for a in _hull_batch(p.verts, p.vmask))
    out = []
    for k in range(p.n):
        if ok[k] and cnt[k] >= 1:
            out.append(hv[k, : cnt[k]].astype(np.float64) + p.ref[k])
        else:
            with _LOCK:
                STATS["hull_host_fallbacks"] += 1
            out.append(oracle.convex_hull_of(arr, int(rows[k])))
    return out


def kernel_buffers(arr: geo.GeometryArray, rows: np.ndarray, d: float):
    """[(H_i, 2) f64 octagonal-buffer hull vertex arrays] (same error bound
    as the oracle's vertex-offset buffer, plus the f32 hull tolerance)."""
    rows = np.asarray(rows, dtype=np.int64)
    if len(rows) == 0:
        return []
    p = pack_features(arr, rows)
    offs = jnp.asarray(oracle.octagon_offsets(d).astype(np.float32))
    K = p.verts.shape[1]
    swept = (p.verts[:, :, None, :] + offs[None, None, :, :]).reshape(
        p.verts.shape[0], K * 8, 2)
    smask = jnp.repeat(p.vmask, 8, axis=1)
    hv, cnt, ok = (np.asarray(a) for a in _hull_batch(swept, smask))
    out = []
    for k in range(p.n):
        if ok[k] and cnt[k] >= 1:
            out.append(hv[k, : cnt[k]].astype(np.float64) + p.ref[k])
        else:
            with _LOCK:
                STATS["hull_host_fallbacks"] += 1
            shape = oracle.buffer_shapes(arr, [int(rows[k])], d)[0]
            out.append(np.asarray(gn.literal_coords(shape)))
    return out


def stats_snapshot() -> Dict[str, int]:
    with _LOCK:
        return dict(STATS)


# -- parity ------------------------------------------------------------------


def _hull_area(pts: np.ndarray) -> float:
    if len(pts) < 3:
        return 0.0
    x, y = pts[:, 0], pts[:, 1]
    return 0.5 * abs(float(np.sum(x * np.roll(y, -1) - np.roll(x, -1) * y)))


def parity_report(arr: geo.GeometryArray, rows: np.ndarray,
                  literal: tuple, d: float = 0.05) -> Dict[str, int]:
    """Kernel-vs-oracle mismatch counts for every catalog function.

    Booleans compare strictly; scalars compare against per-feature forward
    error bounds computed in f64 from the kernel's own term magnitudes (the
    documented bounds — see README). All axes pin 0.
    """
    rows = np.asarray(rows, dtype=np.int64)
    rep = {k: 0 for k in ("st_area", "st_length", "st_centroid",
                          "st_distance", "st_contains", "st_within",
                          "st_intersects", "st_convexhull", "st_buffer")}
    if len(rows) == 0:
        return rep
    u = unary_values(arr, rows)
    o_area = oracle.area(arr, rows)
    o_len = oracle.length(arr, rows)
    o_cx, o_cy = oracle.centroid(arr, rows)
    bb = arr.bboxes()[rows].astype(np.float64)
    ext = np.maximum(np.maximum(bb[:, 2] - bb[:, 0], bb[:, 3] - bb[:, 1]),
                     1e-12)
    mag = np.maximum(np.max(np.abs(bb), axis=1), 1.0)
    # per-feature forward bounds: K f32 ops over terms ≤ ext² (area),
    # ext (length) or ext³/area (centroid), plus the f32 input rounding of
    # shifted coords (≤ ext·2^-24 each)
    nseg = np.asarray([len(gn.feature_segments(arr, int(i))) + 1
                       for i in rows], dtype=np.float64)
    t_area = 64.0 * nseg * _EPS32 * ext * ext + 8.0 * nseg * _EPS32 * ext * mag
    t_len = 64.0 * nseg * _EPS32 * ext + 8.0 * nseg * _EPS32 * mag
    rep["st_area"] = int(np.sum(np.abs(u["area"] - o_area) > t_area))
    rep["st_length"] = int(np.sum(np.abs(u["length"] - o_len) > t_len))
    safe_a = np.maximum(o_area, oracle.AREAL_REL * ext * ext * 0.25)
    t_cen = (256.0 * nseg * _EPS32 * ext * ext * ext) / safe_a \
        + 64.0 * nseg * _EPS32 * ext + 1e-6
    rep["st_centroid"] = int(np.sum(
        np.maximum(np.abs(u["cx"] - o_cx), np.abs(u["cy"] - o_cy)) > t_cen))
    kd = batch_distance(arr, rows, literal)
    od = oracle.distance(arr, rows, literal)
    rep["st_distance"] = int(np.sum(
        np.abs(kd - od) > 2e-4 + 1e-5 * np.abs(od)))
    for name, op, ofn in (
            ("st_intersects", "intersects", oracle.intersects),
            ("st_within", "within", oracle.contains_literal),
            ("st_contains", "contains", oracle.feature_contains)):
        rep[name] = int(np.sum(batch_predicate(arr, rows, op, literal)
                               != ofn(arr, rows, literal)))
    hulls = kernel_hulls(arr, rows)
    for k, i in enumerate(rows):
        oh = oracle.convex_hull_of(arr, int(i))
        tol = 512.0 * _EPS32 * ext[k] * ext[k] + 1e-10
        if abs(_hull_area(hulls[k]) - _hull_area(oh)) > tol:
            rep["st_convexhull"] += 1
    bufs = kernel_buffers(arr, rows, d)
    oshapes = oracle.buffer_shapes(arr, rows, d)
    for k in range(len(rows)):
        oc = np.asarray(gn.literal_coords(oshapes[k]))
        e = ext[k] + 2.0 * d * oracle.BUFFER_SEC
        tol = 512.0 * _EPS32 * e * e + 1e-10
        if abs(_hull_area(bufs[k]) - _hull_area(oc)) > tol:
            rep["st_buffer"] += 1
    return rep
