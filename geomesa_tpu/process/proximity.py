"""Proximity + route search.

≙ reference `ProximitySearchProcess` (features within a distance of a set of
input geometries) and `RouteSearchProcess` (features along a route — the
same computation against a LineString). Bbox prefilter through the index,
exact metric distance refine vectorized over (feature × segment) pairs."""

from __future__ import annotations

from typing import List, Sequence, Tuple, Union

import numpy as np

from geomesa_tpu.features import geometry as geo
from geomesa_tpu.filter import ir
from geomesa_tpu.filter.parser import parse_ecql
from geomesa_tpu.process.geo import (buffered_envelope, haversine_m,
                                     point_segment_distance_m)


def _segments(garr: geo.GeometryArray) -> Tuple[np.ndarray, ...]:
    """All line segments (ax, ay, bx, by) of every ring/line in the input."""
    segs = []
    for r in range(len(garr.ring_offsets) - 1):
        s, e = garr.ring_offsets[r], garr.ring_offsets[r + 1]
        if e - s >= 2:
            c = garr.coords[s:e]
            segs.append(np.concatenate([c[:-1], c[1:]], axis=1))
    if not segs:
        return (np.empty(0),) * 4
    allsegs = np.concatenate(segs, axis=0)
    return allsegs[:, 0], allsegs[:, 1], allsegs[:, 2], allsegs[:, 3]


def proximity_search(planner, inputs: Union[geo.GeometryArray, Sequence[str]],
                     distance_m: float,
                     f: Union[str, ir.Filter, None] = None) -> np.ndarray:
    """Row indices of features within ``distance_m`` of ANY input geometry."""
    if not isinstance(inputs, geo.GeometryArray):
        inputs = geo.GeometryArray.from_wkt(list(inputs))
    if isinstance(f, str):
        f = parse_ecql(f)
    geom = planner.sft.geometry_attribute
    if geom is None:
        raise ValueError("proximity requires a geometry attribute")

    # bbox prefilter: union of per-input buffered boxes (through the index)
    bbs = inputs.bboxes()
    boxes = [ir.BBox(geom.name, *buffered_envelope(*bb, distance_m))
             for bb in bbs]
    pre: ir.Filter = ir.or_filters(boxes) if len(boxes) > 1 else boxes[0]
    if f is not None and not isinstance(f, ir.Include):
        pre = ir.and_filters([f, pre])
    rows = planner.select_indices(pre)
    if len(rows) == 0:
        return rows

    sub = planner.table.take(rows)
    garr = sub.geometry()
    if garr.is_points:
        px, py = garr.point_xy()
    else:
        bb = garr.bboxes()
        px, py = (bb[:, 0] + bb[:, 2]) / 2, (bb[:, 1] + bb[:, 3]) / 2

    keep = np.zeros(len(rows), dtype=bool)
    # point inputs: plain haversine; line/polygon inputs: segment distance
    pts_mask = inputs.type_codes == geo.POINT
    if pts_mask.any():
        starts = inputs.ring_offsets[inputs.part_offsets[inputs.geom_offsets[:-1]]]
        ppts = inputs.coords[starts[pts_mask]]
        d = haversine_m(px[:, None], py[:, None], ppts[None, :, 0], ppts[None, :, 1])
        keep |= (d <= distance_m).any(axis=1)
    if (~pts_mask).any():
        extent_inputs = inputs.take(np.nonzero(~pts_mask)[0])
        ax, ay, bx, by = _segments(extent_inputs)
        if len(ax):
            d = point_segment_distance_m(
                px[:, None], py[:, None],
                ax[None, :], ay[None, :], bx[None, :], by[None, :])
            keep |= (d <= distance_m).any(axis=1)
        # distance-to-boundary misses interior points: polygon containment
        # is distance 0 (≙ the reference's isWithinDistance semantics)
        from geomesa_tpu.filter.geom_numpy import points_in_polygon
        for i in range(len(extent_inputs)):
            code = int(extent_inputs.type_codes[i])
            if code in (geo.POLYGON, geo.MULTIPOLYGON):
                keep |= points_in_polygon(px, py, extent_inputs.shape(i))
    return rows[keep]


def route_search(planner, route_wkt: str, distance_m: float,
                 f: Union[str, ir.Filter, None] = None) -> np.ndarray:
    """Features within ``distance_m`` of the route LineString (≙
    RouteSearchProcess)."""
    return proximity_search(planner, [route_wkt], distance_m, f)
