"""Tube select: space-time corridor search.

≙ reference `TubeSelectProcess` + `TubeBuilder` (geomesa-process/.../tube/):
given an ordered track of (x, y, t) tube points, select features that fall
within ``buffer_m`` of the track's interpolated position at their own
timestamp (± ``time_buffer_ms``). Vectorized: per feature, ``searchsorted``
finds the bracketing tube points, position interpolates linearly, one
haversine pass scores every candidate."""

from __future__ import annotations

from typing import Sequence, Tuple, Union

import numpy as np

from geomesa_tpu.filter import ir
from geomesa_tpu.filter.parser import parse_ecql
from geomesa_tpu.process.geo import buffered_envelope, haversine_m


def tube_select(planner, track: Sequence[Tuple[float, float, object]],
                buffer_m: float, time_buffer_ms: int = 0,
                f: Union[str, ir.Filter, None] = None) -> np.ndarray:
    """Row indices inside the tube. ``track``: ordered (x, y, t) where t is
    epoch ms or datetime64/ISO string."""
    if isinstance(f, str):
        f = parse_ecql(f)
    dtg = planner.sft.dtg_attribute
    geom = planner.sft.geometry_attribute
    if dtg is None or geom is None:
        raise ValueError("tube select requires geometry + date attributes")

    tx = np.asarray([p[0] for p in track], dtype=np.float64)
    ty = np.asarray([p[1] for p in track], dtype=np.float64)
    tt = np.asarray([_ms(p[2]) for p in track], dtype=np.int64)
    order = np.argsort(tt, kind="stable")
    tx, ty, tt = tx[order], ty[order], tt[order]

    # index prefilter: track envelope buffered in space and time
    env = buffered_envelope(float(tx.min()), float(ty.min()),
                            float(tx.max()), float(ty.max()), buffer_m)
    pre: ir.Filter = ir.And((
        ir.BBox(geom.name, *env),
        ir.During(dtg.name, int(tt[0] - time_buffer_ms) - 1,
                  int(tt[-1] + time_buffer_ms) + 1),
    ))
    if f is not None and not isinstance(f, ir.Include):
        pre = ir.and_filters([f, pre])
    rows = planner.select_indices(pre)
    if len(rows) == 0:
        return rows

    sub = planner.table.take(rows)
    garr = sub.geometry()
    if garr.is_points:
        px, py = garr.point_xy()
    else:
        bb = garr.bboxes()
        px, py = (bb[:, 0] + bb[:, 2]) / 2, (bb[:, 1] + bb[:, 3]) / 2
    pt = np.asarray(sub.columns[dtg.name], dtype=np.int64)

    # clamp each feature time into the track span (time_buffer permitting),
    # interpolate the track position at that instant
    t_lo, t_hi = tt[0], tt[-1]
    in_time = (pt >= t_lo - time_buffer_ms) & (pt <= t_hi + time_buffer_ms)
    tc = np.clip(pt, t_lo, t_hi)
    hi = np.clip(np.searchsorted(tt, tc, side="left"), 1, len(tt) - 1)
    lo = hi - 1
    span = (tt[hi] - tt[lo]).astype(np.float64)
    w = np.where(span > 0, (tc - tt[lo]) / np.where(span > 0, span, 1.0), 0.0)
    ix = tx[lo] + w * (tx[hi] - tx[lo])
    iy = ty[lo] + w * (ty[hi] - ty[lo])

    d = haversine_m(px, py, ix, iy)
    return rows[in_time & (d <= buffer_m)]


def _ms(t) -> int:
    if isinstance(t, (int, np.integer)):
        return int(t)
    return int(np.datetime64(t, "ms").astype(np.int64))
