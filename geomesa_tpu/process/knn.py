"""K-nearest-neighbor search.

≙ reference `KNearestNeighborSearchProcess` (geomesa-process/.../query/
KNearestNeighborSearchProcess.scala): iterative expanding-radius queries
against the index until enough candidates exist, then exact distance
ranking. The radius doubling runs cheap device COUNTS (one fused scan each);
only the final candidate set is pulled to the host for ranking — and the
guarantee pass re-queries at the k-th distance so no closer feature outside
the last bbox is missed."""

from __future__ import annotations

from typing import Optional, Union

import numpy as np

from geomesa_tpu.filter import ir
from geomesa_tpu.filter.parser import parse_ecql
from geomesa_tpu.process.geo import expand_bbox, haversine_m


def knn(planner, x: float, y: float, k: int,
        f: Union[str, ir.Filter, None] = None,
        initial_radius_m: float = 1000.0, max_doublings: int = 20):
    """(row indices, distances in meters) of the k features nearest (x, y),
    optionally restricted by a filter."""
    if isinstance(f, str):
        f = parse_ecql(f)
    geom = planner.sft.geometry_attribute
    if geom is None:
        raise ValueError("KNN requires a geometry attribute")

    def with_bbox(radius_m):
        bbox = ir.BBox(geom.name, *expand_bbox(x, y, radius_m))
        return bbox if f is None or isinstance(f, ir.Include) \
            else ir.and_filters([f, bbox])

    # expanding-radius count loop (device-side counts)
    radius = float(initial_radius_m)
    whole_world = False
    for _ in range(max_doublings):
        if planner.count(with_bbox(radius)) >= k:
            break
        radius *= 2
        xmin, ymin, xmax, ymax = expand_bbox(x, y, radius)
        if (xmin, ymin, xmax, ymax) == (-180.0, -90.0, 180.0, 90.0):
            whole_world = True
            break

    rows, dists = _rank(planner, with_bbox(radius) if not whole_world else
                        (f or ir.Include()), x, y, k)
    if len(rows) == 0 or whole_world:
        return rows, dists
    # guarantee: the k-th distance may exceed the bbox's inscribed circle —
    # re-query at that radius so boundary-adjacent closer points are seen
    dk = float(dists[-1])
    if dk > radius:
        rows, dists = _rank(planner, with_bbox(dk * 1.001), x, y, k)
    return rows, dists


def _rank(planner, f, x, y, k):
    rows = planner.select_indices(f)
    if len(rows) == 0:
        return rows, np.empty(0)
    sub = planner.table.take(rows)
    garr = sub.geometry()
    if garr.is_points:
        gx, gy = garr.point_xy()
    else:
        bb = garr.bboxes()
        gx, gy = (bb[:, 0] + bb[:, 2]) / 2, (bb[:, 1] + bb[:, 3]) / 2
    d = haversine_m(gx, gy, x, y)
    take = min(k, len(d))
    part = np.argpartition(d, take - 1)[:take]
    order = part[np.argsort(d[part], kind="stable")]
    return rows[order], d[order]
