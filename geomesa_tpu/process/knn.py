"""K-nearest-neighbor search.

≙ reference `KNearestNeighborSearchProcess` (geomesa-process/.../query/
KNearestNeighborSearchProcess.scala): iterative expanding-radius queries
against the index until enough candidates exist, then exact distance ranking.

TPU shape of the search: the radius-doubling "loop" is not a loop of blocking
queries — every candidate radius shares one compiled count kernel (same box
shape), so ALL radii dispatch asynchronously up front and a single stacked
readback returns every count (one host↔device round trip for the whole
doubling schedule). The final candidate pull sizes its select capacity from
the already-known count, so no overflow-retry rescans happen; the guarantee
pass re-queries at the k-th distance so no closer feature outside the last
bbox is missed.
"""

from __future__ import annotations

from typing import Optional, Union

import numpy as np

from geomesa_tpu.filter import ir
from geomesa_tpu.filter.parser import parse_ecql
from geomesa_tpu.process.geo import expand_bbox, haversine_m

_WORLD = (-180.0, -90.0, 180.0, 90.0)


def knn(planner, x: float, y: float, k: int,
        f: Union[str, ir.Filter, None] = None,
        initial_radius_m: float = 1000.0, max_doublings: int = 20):
    """(row indices, distances in meters) of the k features nearest (x, y),
    optionally restricted by a filter."""
    if isinstance(f, str):
        f = parse_ecql(f)
    geom = planner.sft.geometry_attribute
    if geom is None:
        raise ValueError("KNN requires a geometry attribute")

    def with_bbox(radius_m):
        bbox = ir.BBox(geom.name, *expand_bbox(x, y, radius_m))
        return bbox if f is None or isinstance(f, ir.Include) \
            else ir.and_filters([f, bbox])

    # doubling schedule (stops once a bbox covers the world)
    radii = []
    r = float(initial_radius_m)
    for _ in range(max_doublings):
        radii.append(r)
        if expand_bbox(x, y, r) == _WORLD:
            break
        r *= 2

    counts = _pipelined_counts(planner, with_bbox, radii)
    enough = np.nonzero(counts >= k)[0]
    if len(enough) == 0:
        # even the widest bbox held < k — rank whatever the widest query has
        radius, expected = radii[-1], int(counts[-1])
        whole_world = expand_bbox(x, y, radius) == _WORLD
    else:
        i = int(enough[0])
        radius, expected = radii[i], int(counts[i])
        whole_world = False

    rows, dists = _rank(planner,
                        (f or ir.Include()) if whole_world else with_bbox(radius),
                        x, y, k, capacity=expected)
    if len(rows) == 0 or whole_world:
        return rows, dists
    # guarantee: the k-th distance may exceed the bbox's inscribed circle —
    # re-query at that radius so boundary-adjacent closer points are seen
    dk = float(dists[-1])
    if dk > radius:
        rows, dists = _rank(planner, with_bbox(dk * 1.001), x, y, k)
    return rows, dists


def _pipelined_counts(planner, with_bbox, radii) -> np.ndarray:
    """Counts for every radius in ONE round trip when the plan allows it
    (device-exact primary boxes); otherwise sequential blocking counts."""
    plan = planner.plan(with_bbox(radii[0]))
    if (not plan.empty and plan.primary_kind in ("point_boxes", "bbox_overlap")
            and plan.residual_host is None and plan.candidate_slices is None
            and plan.index is not None):
        from geomesa_tpu.filter.extract import extract_bboxes
        from geomesa_tpu.index.spatial import _boxes_fp62
        geom = planner.sft.geometry_attribute.name
        # rebuild only the box constants per radius; a radius whose bbox
        # splits (antimeridian) falls back to the sequential path
        raws = [_boxes_fp62(extract_bboxes(with_bbox(r), geom).boxes)
                for r in radii]
        if all(len(b) == 1 for b in raws):
            boxes = np.concatenate(raws, axis=0)
            return plan.index.kernels.counts_multi(
                plan.primary_kind, boxes, plan.windows,
                plan.residual_device)
    return np.array([planner.count(with_bbox(r)) for r in radii])


def _rank(planner, f, x, y, k, capacity: Optional[int] = None):
    rows = planner.select_indices(f, capacity=capacity)
    if len(rows) == 0:
        return rows, np.empty(0)
    garr = planner.table.geometry()
    if garr.is_points:
        gx, gy = garr.point_xy()
        gx, gy = gx[rows], gy[rows]
    else:
        bb = garr.bboxes()[rows]
        gx, gy = (bb[:, 0] + bb[:, 2]) / 2, (bb[:, 1] + bb[:, 3]) / 2
    d = haversine_m(gx, gy, x, y)
    take = min(k, len(d))
    part = np.argpartition(d, take - 1)[:take]
    order = part[np.argsort(d[part], kind="stable")]
    return rows[order], d[order]
