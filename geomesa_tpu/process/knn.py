"""K-nearest-neighbor search.

≙ reference `KNearestNeighborSearchProcess` (geomesa-process/.../query/
KNearestNeighborSearchProcess.scala): the reference iterates expanding-radius
index queries because a storage scan prices by key range. A TPU prices by
full-array reductions, so the whole search is ONE fused kernel: mask (the
optional filter) → haversine distance → `lax.top_k` → a k-sized readback.
No radius schedule, no candidate pull, no guarantee re-query.

Exactness: device distances are f32, so the kernel returns a top-`m` margin
(m >= 2k) and the host re-ranks those m candidates in f64 — rank noise from
f32 rounding (~1e-7 relative) cannot push a true top-k member out of a 2k
margin unless distances tie at that precision, in which case either ordering
is a correct KNN result.

The expanding-radius path survives as the fallback for plans the device
kernel can't serve (extent layers without point coords, host residuals,
k beyond the kernel tier cap).

Under a sharded cluster this module answers the LOCAL shard only;
cluster/exec.py's ClusterScan.knn wraps it in the bounded radius
exchange (each shard proves an upper bound from its local kth distance,
then ships only candidates inside the agreed radius) and falls back to
these single-process paths verbatim when the runtime is inactive.
"""

from __future__ import annotations

import math
import weakref
from typing import Optional, Union

import numpy as np

from geomesa_tpu.filter import ir
from geomesa_tpu.filter.parser import parse_ecql
from geomesa_tpu.metrics import REGISTRY as _metrics
from geomesa_tpu.process.geo import expand_bbox, haversine_m

_WORLD = (-180.0, -90.0, 180.0, 90.0)
_MAX_DEVICE_K = 2048

# per-planner KNN state: the radius that last satisfied the candidate
# target (keyed by target, so k=10 and k=500 seed independently) and the
# last padded block tier. Each extra radius round is a full host
# plan+cover pass (the measured cfg4 cost at 100M — see the perf watch
# report perf/reports/cfg4_knn_regression.json), and a tier flip between
# adjacent powers of two is a fresh XLA compile (kernels.recompiles), so
# both memos directly buy back blocking latency. Weak: a dropped planner
# frees its state.
_MEMO: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def _memo_for(planner) -> dict:
    m = _MEMO.get(planner)
    if m is None:
        m = {"radii": {}, "tier": 0}
        _MEMO[planner] = m
    return m


def _stable_tier_blocks(memo: dict, blocks: np.ndarray) -> np.ndarray:
    """Pad candidate blocks to a hysteresis-stable power-of-two tier: a
    query whose cover straddles a pow2 boundary reuses the NEIGHBORING
    query's (compiled) tier instead of flip-flopping between two jit
    signatures — the recompile churn the kernels.recompiles counter made
    visible. Padded ids are -1 (masked out by the kernel)."""
    nb = max(8, 1 << max(0, len(blocks) - 1).bit_length())
    tier = memo.get("tier", 0)
    if tier and nb < tier <= 2 * nb:
        nb = tier  # round UP to the remembered tier (<= 2x the work)
    memo["tier"] = nb
    out = np.full(nb, -1, dtype=np.int32)
    out[: len(blocks)] = blocks
    return out


def knn(planner, x: float, y: float, k: int,
        f: Union[str, ir.Filter, None] = None,
        initial_radius_m: float = 1000.0, max_doublings: int = 20):
    """(row indices, distances in meters) of the k features nearest (x, y),
    optionally restricted by a filter."""
    if isinstance(f, str):
        f = parse_ecql(f)
    geom = planner.sft.geometry_attribute
    if geom is None:
        raise ValueError("KNN requires a geometry attribute")
    if k <= 0:
        return np.empty(0, dtype=np.int64), np.empty(0)

    plan = planner.plan(f if f is not None else ir.Include())
    device_ok = (plan.device_exact and "xf" in plan.index.device.columns
                 and k <= _MAX_DEVICE_K)
    if device_ok:
        return _device_knn(planner, plan, x, y, k, f=f,
                           initial_radius_m=initial_radius_m)
    if plan.empty:
        return np.empty(0, dtype=np.int64), np.empty(0)
    return _radius_knn(planner, x, y, k, f, initial_radius_m, max_doublings)


def _device_knn(planner, plan, x: float, y: float, k: int,
                f=None, initial_radius_m: float = 1000.0):
    """Device KNN with a host-driven radius bound.

    The search radius grows HOST-SIDE: the range cover's candidate-row count
    (pure host binary searches over the sorted keys — zero device traffic)
    tells us when a bbox plausibly holds >= k matches. One device dispatch
    then runs distance + top_k over just the candidate blocks (lax.top_k is
    a full sort on TPU, so operand size is everything: candidate blocks make
    KNN cost flat in table size). The classic inscribed-circle guarantee
    re-runs wider when the k-th distance exceeds the radius — so results are
    exactly the global k nearest."""
    m = max(16, 1 << (max(2 * k, k + 16) - 1).bit_length())
    geom = planner.sft.geometry_attribute
    index = plan.index

    def with_bbox(radius_m):
        bbox = ir.BBox(geom.name, *expand_bbox(x, y, radius_m))
        return bbox if f is None or isinstance(f, ir.Include) \
            else ir.and_filters([f, bbox])

    memo = _memo_for(planner)
    target = max(32 * k, 2048)
    fkey = ("full", target)
    uses = memo.get(fkey)
    if uses is not None and uses < 16:
        # last probe ended at the full-table kernel (cover declined before
        # the candidate target — the small-table / wide-data regime):
        # skip the radius walk entirely. Re-probe every 16th query so a
        # grown table regains the pruned path; a stale choice is still
        # exact, just unpruned.
        memo[fkey] = uses + 1
        _metrics.inc("knn.radius_memo_hits")
        return _full_table_knn(planner, plan, index, x, y, k, m)
    memo.pop(fkey, None)
    seeded = memo["radii"].get(target)
    r = float(seeded if seeded is not None else initial_radius_m)
    first_round = True
    prev_rows = -1
    for _ in range(40):
        _metrics.inc("knn.plan_rounds")
        whole_world = expand_bbox(x, y, r) == _WORLD
        plan_r = planner.plan(plan.full_filter if whole_world else with_bbox(r))
        if not (plan_r.residual_host is None and plan_r.candidate_slices is None
                and plan_r.index is index):
            break  # composition changed the plan shape: full-table kernel
        blocks = planner._pruned_blocks(plan_r)
        if blocks is None:
            if first_round and seeded is not None:
                # stale memo (table shrank / cover now declines at this
                # radius): restart the ordinary schedule, don't give up
                # the pruned path
                r = float(initial_radius_m)
                seeded = None
                first_round = False
                continue
            break  # no cover (wide bbox / tiny table): full-table kernel
        # candidate rows are free to evaluate (host binary searches), so aim
        # well past k: a generous candidate set makes the inscribed-circle
        # guarantee pass on the FIRST dispatch almost always — each failed
        # guarantee costs a full device round trip, each extra radius step
        # a full host plan+cover pass (the dominant cfg4 cost at 100M on a
        # single-core host — which is why the growth below is density-
        # scaled and the landing radius is memoized per planner)
        rows = plan_r.explain.get("candidate_rows", 0)
        enough = rows >= target
        if not (enough or whole_world):
            # candidate rows grow ~r^2 in locally-uniform data: jump
            # toward the radius that should hold ~1.5x the target instead
            # of walking a blind schedule. A stagnant count means the
            # cover's resolution hasn't moved yet — fall back to the x8
            # step (never slower than the pre-memo schedule).
            if rows > 0 and rows != prev_rows:
                grow = min(max(math.sqrt(1.5 * target / rows), 2.0), 8.0)
            else:
                grow = 8.0
            prev_rows = rows
            r *= grow
            first_round = False
            continue
        if first_round and seeded is not None:
            _metrics.inc("knn.radius_memo_hits")
        memo["radii"][target] = r
        from geomesa_tpu.index import prune as _prune
        _metrics.inc("knn.device_dispatches")
        dists, pos = index.kernels.topk_nearest_blocks(
            plan_r.primary_kind, plan_r.boxes_loose, plan_r.windows,
            plan_r.residual_device, x, y, m,
            _stable_tier_blocks(memo, blocks), _prune.BLOCK_SIZE)
        valid = np.isfinite(dists)
        kth_ok = valid.sum() >= k and float(np.sort(dists[valid])[k - 1]) <= r
        if whole_world or kth_ok:
            return _exact_rerank(planner, index, pos[valid], x, y, k)
        # fewer than k in radius, or the k-th may lie outside the bbox
        r = max(r * 4, float(np.sort(dists[valid])[min(valid.sum(), k) - 1])
                * 1.001 if valid.any() else r * 4)
        first_round = False
    else:
        return np.empty(0, dtype=np.int64), np.empty(0)

    memo[fkey] = 1  # remember the full-table outcome for the neighbors
    return _full_table_knn(planner, plan, index, x, y, k, m)


def _full_table_knn(planner, plan, index, x, y, k, m):
    _metrics.inc("knn.device_dispatches")
    dists, pos = index.kernels.topk_nearest(
        plan.primary_kind, plan.boxes_loose, plan.windows,
        plan.residual_device, x, y, m)
    valid = np.isfinite(dists)
    return _exact_rerank(planner, index, pos[valid], x, y, k)


def _exact_rerank(planner, index, pos: np.ndarray, x: float, y: float, k: int):
    rows = index.map_rows(pos.astype(np.int64))
    if len(rows) == 0:
        return rows, np.empty(0)
    gx, gy = planner.table.geometry().point_xy()
    d = haversine_m(gx[rows], gy[rows], x, y)
    take = min(k, len(d))
    part = np.argpartition(d, take - 1)[:take]
    order = part[np.argsort(d[part], kind="stable")]
    return rows[order], d[order]


# -- expanding-radius fallback (reference-shaped) ---------------------------


def _radius_knn(planner, x, y, k, f, initial_radius_m, max_doublings):
    geom = planner.sft.geometry_attribute

    def with_bbox(radius_m):
        bbox = ir.BBox(geom.name, *expand_bbox(x, y, radius_m))
        return bbox if f is None or isinstance(f, ir.Include) \
            else ir.and_filters([f, bbox])

    # doubling schedule (stops once a bbox covers the world); always at
    # least the initial radius, so max_doublings < 1 degrades gracefully
    radii = []
    r = float(initial_radius_m)
    for _ in range(max(1, max_doublings)):
        radii.append(r)
        if expand_bbox(x, y, r) == _WORLD:
            break
        r *= 2

    counts = _pipelined_counts(planner, with_bbox, radii)
    enough = np.nonzero(counts >= k)[0]
    if len(enough) == 0:
        # even the widest bbox held < k — rank whatever the widest query has
        radius, expected = radii[-1], int(counts[-1])
        whole_world = expand_bbox(x, y, radius) == _WORLD
    else:
        i = int(enough[0])
        radius, expected = radii[i], int(counts[i])
        whole_world = False

    rows, dists = _rank(planner,
                        (f or ir.Include()) if whole_world else with_bbox(radius),
                        x, y, k, capacity=expected)
    if len(rows) == 0 or whole_world:
        return rows, dists
    # guarantee: the k-th distance may exceed the bbox's inscribed circle —
    # re-query at that radius so boundary-adjacent closer points are seen
    dk = float(dists[-1])
    if dk > radius:
        rows, dists = _rank(planner, with_bbox(dk * 1.001), x, y, k)
    return rows, dists


def _pipelined_counts(planner, with_bbox, radii) -> np.ndarray:
    """Counts for every radius in ONE round trip when the plan allows it
    (device-exact primary boxes); otherwise sequential blocking counts."""
    plan = planner.plan(with_bbox(radii[0]))
    if (not plan.empty and plan.primary_kind in ("point_boxes", "bbox_overlap")
            and plan.residual_host is None and plan.candidate_slices is None
            and plan.index is not None):
        from geomesa_tpu.filter.extract import extract_bboxes
        from geomesa_tpu.index.spatial import _boxes_fp62
        geom = planner.sft.geometry_attribute.name
        # rebuild only the box constants per radius; a radius whose bbox
        # splits (antimeridian) falls back to the sequential path
        raws = [_boxes_fp62(extract_bboxes(with_bbox(r), geom).boxes)
                for r in radii]
        if all(len(b) == 1 for b in raws):
            boxes = np.concatenate(raws, axis=0)
            return plan.index.kernels.counts_multi(
                plan.primary_kind, boxes, plan.windows,
                plan.residual_device)
    return np.array([planner.count(with_bbox(r)) for r in radii])


def _rank(planner, f, x, y, k, capacity: Optional[int] = None):
    rows = planner.select_indices(f, capacity=capacity)
    if len(rows) == 0:
        return rows, np.empty(0)
    garr = planner.table.geometry()
    if garr.is_points:
        gx, gy = garr.point_xy()
        gx, gy = gx[rows], gy[rows]
    else:
        bb = garr.bboxes()[rows]
        gx, gy = (bb[:, 0] + bb[:, 2]) / 2, (bb[:, 1] + bb[:, 3]) / 2
    d = haversine_m(gx, gy, x, y)
    take = min(k, len(d))
    part = np.argpartition(d, take - 1)[:take]
    order = part[np.argsort(d[part], kind="stable")]
    return rows[order], d[order]
