"""Geodesic helpers for the process layer (vectorized)."""

from __future__ import annotations

import numpy as np

EARTH_R_M = 6371008.8


def haversine_m(x1, y1, x2, y2) -> np.ndarray:
    """Great-circle distance in meters between lon/lat degree points."""
    lon1, lat1, lon2, lat2 = (np.radians(np.asarray(a, dtype=np.float64))
                              for a in (x1, y1, x2, y2))
    dlat = lat2 - lat1
    dlon = lon2 - lon1
    a = np.sin(dlat / 2) ** 2 + np.cos(lat1) * np.cos(lat2) * np.sin(dlon / 2) ** 2
    return 2 * EARTH_R_M * np.arcsin(np.sqrt(np.clip(a, 0.0, 1.0)))


def meters_to_degrees(m: float, lat: float) -> tuple:
    """(dlon, dlat) spans covering a radius of ``m`` meters at ``lat``."""
    dlat = m / 111_320.0
    dlon = m / (111_320.0 * max(0.01, np.cos(np.radians(lat))))
    return dlon, dlat


def expand_bbox(x: float, y: float, radius_m: float) -> tuple:
    dlat = radius_m / 111_320.0
    # longitude degrees shrink toward the poles: buffer at the WIDEST
    # latitude the box reaches, or the prefilter under-covers high latitudes
    lat_w = min(89.0, abs(y) + dlat)
    dlon, _ = meters_to_degrees(radius_m, lat_w)
    return (max(-180.0, x - dlon), max(-90.0, y - dlat),
            min(180.0, x + dlon), min(90.0, y + dlat))


def buffered_envelope(xmin: float, ymin: float, xmax: float, ymax: float,
                      radius_m: float) -> tuple:
    """Envelope grown by ``radius_m`` on every side, with the longitude
    buffer computed at the envelope's widest latitude."""
    dlat = radius_m / 111_320.0
    lat_w = min(89.0, max(abs(ymin - dlat), abs(ymax + dlat)))
    dlon, _ = meters_to_degrees(radius_m, lat_w)
    return (max(-180.0, xmin - dlon), max(-90.0, ymin - dlat),
            min(180.0, xmax + dlon), min(90.0, ymax + dlat))


def point_segment_distance_m(px, py, ax, ay, bx, by) -> np.ndarray:
    """Distance from points (px, py) to segments (a→b), all lon/lat degrees.
    Uses a local equirectangular projection around each segment — accurate to
    well under 1% for segments below a few hundred km, which is the tube/
    route regime (≙ the reference evaluating JTS distance in degrees, but
    metric)."""
    px, py, ax, ay, bx, by = (np.asarray(v, dtype=np.float64)
                              for v in (px, py, ax, ay, bx, by))
    lat0 = np.radians((ay + by) / 2)
    kx = 111_320.0 * np.cos(lat0)
    ky = 111_320.0
    pxm, pym = (px - ax) * kx, (py - ay) * ky
    bxm, bym = (bx - ax) * kx, (by - ay) * ky
    seg2 = bxm ** 2 + bym ** 2
    t = np.where(seg2 > 0, (pxm * bxm + pym * bym) / np.where(seg2 > 0, seg2, 1.0), 0.0)
    t = np.clip(t, 0.0, 1.0)
    dx, dy = pxm - t * bxm, pym - t * bym
    return np.sqrt(dx ** 2 + dy ** 2)
