"""Small analytic processes.

≙ reference `Point2PointProcess` (point sequences → per-track LineStrings),
`UniqueProcess` (distinct attribute values + counts), `HashAttributeProcess`
/ `HashAttributeColorProcess` (stable hash buckets for styling), and
`DateOffsetProcess` (shift a date attribute). All columnar one-pass ops."""

from __future__ import annotations

from typing import List, Optional, Tuple, Union

import numpy as np

from geomesa_tpu.features.table import FeatureTable, StringColumn
from geomesa_tpu.filter import ir
from geomesa_tpu.stats.sketches import hash64


def point2point(planner, track_attr: str, f: Union[str, ir.Filter] = "INCLUDE",
                break_on_day: bool = False):
    """Per-track LineStrings from time-ordered points (≙ Point2PointProcess).
    Returns a list of (track value, LineString WKT, n_points); tracks with
    fewer than 2 points are dropped. break_on_day splits tracks at UTC day
    boundaries like the reference's breakOnDay flag."""
    dtg = planner.sft.dtg_attribute
    if dtg is None:
        raise ValueError("point2point requires a date attribute")
    rows = planner.select_indices(f)
    sub = planner.table.take(rows)
    x, y = sub.geometry().point_xy()
    t = np.asarray(sub.columns[dtg.name], dtype=np.int64)
    col = sub.columns[track_attr]
    keys = col.codes if isinstance(col, StringColumn) else np.asarray(col)

    day = t // 86_400_000 if break_on_day else np.zeros_like(t)
    order = np.lexsort((t, day, keys))
    keys_s, day_s = keys[order], day[order]
    xs, ys = x[order], y[order]
    breaks = np.nonzero((np.diff(keys_s) != 0) | (np.diff(day_s) != 0))[0] + 1
    out = []
    for s, e in zip(np.r_[0, breaks], np.r_[breaks, len(keys_s)]):
        if e - s < 2:
            continue
        val = col.vocab[keys_s[s]] if isinstance(col, StringColumn) else keys_s[s].item()
        coords = ", ".join(f"{xs[i]:.9g} {ys[i]:.9g}" for i in range(s, e))
        out.append((val, f"LINESTRING ({coords})", int(e - s)))
    return out


def unique_values(planner, attr: str, f: Union[str, ir.Filter] = "INCLUDE",
                  sort_by_count: bool = False) -> List[Tuple[object, int]]:
    """Distinct values + counts (≙ UniqueProcess), via the stats scan."""
    from geomesa_tpu.aggregates.stats_scan import run_stat
    stat = run_stat(planner, f'Enumeration("{attr}")', f)
    items = list(stat.counts.items())
    return sorted(items, key=(lambda kv: -kv[1]) if sort_by_count else (lambda kv: str(kv[0])))


def hash_attribute(planner, attr: str, buckets: int,
                   f: Union[str, ir.Filter] = "INCLUDE") -> np.ndarray:
    """Stable per-feature hash bucket of an attribute (≙
    HashAttributeProcess; styling/partitioning helper)."""
    rows = planner.select_indices(f)
    sub = planner.table.take(rows)
    col = sub.columns[attr]
    if isinstance(col, StringColumn):
        vocab_h = hash64(np.asarray(col.vocab, dtype=object))
        h = vocab_h[col.codes]
    else:
        h = hash64(np.asarray(col))
    return (h % np.uint64(buckets)).astype(np.int32)


def date_offset(planner, offset_ms: int, f: Union[str, ir.Filter] = "INCLUDE",
                attr: Optional[str] = None) -> FeatureTable:
    """Matching rows with the date attribute shifted (≙ DateOffsetProcess)."""
    dtg_attr = attr or (planner.sft.dtg_attribute.name
                        if planner.sft.dtg_attribute else None)
    if dtg_attr is None:
        raise ValueError("date_offset requires a date attribute")
    rows = planner.select_indices(f)
    sub = planner.table.take(rows)
    sub.columns[dtg_attr] = np.asarray(sub.columns[dtg_attr], dtype=np.int64) + offset_ms
    return sub
