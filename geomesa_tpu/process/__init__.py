"""Analytic processes over the query engine.

≙ reference `geomesa-process` (SURVEY.md §2.9): the WPS surface re-shaped as
plain functions against a planner — KNN, proximity/route search, tube
(space-time corridor) select, point2point track building, unique values,
hash/date-offset utilities. Density, sampling, stats and BIN conversion
live in `geomesa_tpu.aggregates` (they are scan hints, as in the reference).
"""

from geomesa_tpu.process.geo import haversine_m, point_segment_distance_m
from geomesa_tpu.process.knn import knn
from geomesa_tpu.process.misc import (date_offset, hash_attribute, point2point,
                                      unique_values)
from geomesa_tpu.process.proximity import proximity_search, route_search
from geomesa_tpu.process.tube import tube_select

__all__ = ["date_offset", "hash_attribute", "haversine_m", "knn",
           "point2point", "point_segment_distance_m", "proximity_search",
           "route_search", "tube_select", "unique_values"]
