"""Batched host geometry predicates over candidate sets.

The device scan returns candidate row sets; the residual spatial refine then
has to evaluate exact geometry predicates over tens of thousands of features.
The reference pushes this refinement next to the data (the server-side
full-filter path of FilterTransformIterator / AggregatingScan.scala:82); the
host equivalent here must therefore be *batched*, not a per-feature Python
loop: all candidates' coordinates and boundary segments are flattened into
"soups" tagged with a candidate ordinal, every geometric test runs as one
(chunked) numpy broadcast, and per-feature verdicts come back via
``bincount``/``reduceat`` group reductions.

Semantics are identical to the scalar oracles in ``filter.geom_numpy``
(property-tested); these functions are the production path, the scalar ones
remain the reference oracle.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

from geomesa_tpu.features import geometry as geo
from geomesa_tpu.filter import geom_numpy as gn

# max elements in any broadcast temporary (~32 MB of f64)
_CHUNK = 4_000_000

_expand_slices = geo.expand_slices


def gather_coords(arr: geo.GeometryArray, idx: np.ndarray
                  ) -> Tuple[np.ndarray, np.ndarray]:
    """All coordinates of the selected features: ((M, 2) f64, (M,) ordinal).

    Ordinals index into ``idx`` (0..C-1) and come out grouped ascending —
    features own contiguous coordinate slices by construction.
    """
    idx = np.asarray(idx, dtype=np.int64)
    starts = arr.ring_offsets[arr.part_offsets[arr.geom_offsets[idx]]]
    ends = arr.ring_offsets[arr.part_offsets[arr.geom_offsets[idx + 1]]]
    counts = ends - starts
    sel = _expand_slices(starts, counts)
    fid = np.repeat(np.arange(len(idx), dtype=np.int64), counts)
    return arr.coords[sel], fid


def build_segments(arr: geo.GeometryArray, idx: np.ndarray
                   ) -> Tuple[np.ndarray, np.ndarray]:
    """Boundary-segment soup of the selected features.

    Returns ((S, 4) f64 [x1 y1 x2 y2], (S,) ordinal), ordinals grouped
    ascending. Rings of polygonal features gain a closing segment when stored
    unclosed (a degenerate duplicate is never added).
    """
    idx = np.asarray(idx, dtype=np.int64)
    c = len(idx)
    g0, g1 = arr.geom_offsets[idx], arr.geom_offsets[idx + 1]
    r0, r1 = arr.part_offsets[g0], arr.part_offsets[g1]
    nrings = r1 - r0
    rings = _expand_slices(r0, nrings)
    if len(rings) == 0:
        return np.zeros((0, 4)), np.zeros(0, dtype=np.int64)
    ring_fid = np.repeat(np.arange(c, dtype=np.int64), nrings)
    s, e = arr.ring_offsets[rings], arr.ring_offsets[rings + 1]
    k = e - s
    nseg = np.maximum(k - 1, 0)
    a = _expand_slices(s, nseg)
    segs = np.concatenate([arr.coords[a], arr.coords[a + 1]], axis=1)
    seg_fid = np.repeat(ring_fid, nseg)

    is_poly = np.isin(arr.type_codes[idx], (geo.POLYGON, geo.MULTIPOLYGON))
    need = is_poly[ring_fid] & (k >= 3) \
        & np.any(arr.coords[s] != arr.coords[np.maximum(e - 1, s)], axis=1)
    if np.any(need):
        close = np.concatenate([arr.coords[e[need] - 1], arr.coords[s[need]]],
                               axis=1)
        segs = np.concatenate([segs, close])
        seg_fid = np.concatenate([seg_fid, ring_fid[need]])
        order = np.argsort(seg_fid, kind="stable")
        segs, seg_fid = segs[order], seg_fid[order]
    return segs, seg_fid


# -- group reductions --------------------------------------------------------


def _any_per_feature(fid: np.ndarray, hits: np.ndarray, c: int) -> np.ndarray:
    """bool (c,): any item with this ordinal is True."""
    if len(fid) == 0:
        return np.zeros(c, dtype=bool)
    return np.bincount(fid[hits], minlength=c).astype(bool)


def _min_per_feature(fid: np.ndarray, vals: np.ndarray, c: int) -> np.ndarray:
    """float (c,): min value per ordinal (inf where a feature has no items).
    Requires ``fid`` grouped ascending (gather_coords/build_segments order)."""
    out = np.full(c, np.inf)
    if len(fid) == 0:
        return out
    present, first = np.unique(fid, return_index=True)
    out[present] = np.minimum.reduceat(vals, first)
    return out


# -- chunked broadcasts ------------------------------------------------------


def _pip_chunked(px: np.ndarray, py: np.ndarray, literal: tuple) -> np.ndarray:
    """points_in_polygon with bounded temporaries."""
    n = len(px)
    nv = max(1, len(gn.literal_coords(literal)))
    step = max(1, _CHUNK // nv)
    if n <= step:
        return gn.points_in_polygon(px, py, literal)
    out = np.empty(n, dtype=bool)
    for i in range(0, n, step):
        out[i:i + step] = gn.points_in_polygon(px[i:i + step], py[i:i + step],
                                               literal)
    return out


def _point_eq_chunked(coords: np.ndarray, lc: np.ndarray) -> np.ndarray:
    """Any-vertex == any-literal-point equality with bounded temporaries
    (the raw (n_coords x n_literal) broadcast blows the temp budget for a
    large candidate set against a large MULTIPOINT literal)."""
    n = len(coords)
    step = max(1, _CHUNK // max(1, len(lc)))
    if n <= step:
        return np.any((coords[:, None, 0] == lc[None, :, 0])
                      & (coords[:, None, 1] == lc[None, :, 1]), axis=1)
    out = np.empty(n, dtype=bool)
    for i in range(0, n, step):
        ch = coords[i:i + step]
        out[i:i + step] = np.any((ch[:, None, 0] == lc[None, :, 0])
                                 & (ch[:, None, 1] == lc[None, :, 1]), axis=1)
    return out


def _vertex_dist_chunked(coords: np.ndarray, lc: np.ndarray) -> np.ndarray:
    """Min vertex-to-literal-point distance with bounded temporaries."""
    n = len(coords)
    step = max(1, _CHUNK // max(1, len(lc)))
    if n <= step:
        return np.min(np.hypot(coords[:, None, 0] - lc[None, :, 0],
                               coords[:, None, 1] - lc[None, :, 1]), axis=1)
    out = np.empty(n)
    for i in range(0, n, step):
        ch = coords[i:i + step]
        out[i:i + step] = np.min(np.hypot(ch[:, None, 0] - lc[None, :, 0],
                                          ch[:, None, 1] - lc[None, :, 1]),
                                 axis=1)
    return out


def _on_segments_chunked(px, py, segs: np.ndarray) -> np.ndarray:
    n = len(px)
    ns = max(1, len(segs))
    step = max(1, _CHUNK // ns)
    if n <= step:
        return gn._points_on_segments(px, py, segs)
    out = np.empty(n, dtype=bool)
    for i in range(0, n, step):
        out[i:i + step] = gn._points_on_segments(px[i:i + step],
                                                 py[i:i + step], segs)
    return out


def _points_in_features(lx: np.ndarray, ly: np.ndarray, segs: np.ndarray,
                        seg_fid: np.ndarray, c: int) -> np.ndarray:
    """bool (c,): any of the query points falls inside the feature by
    crossing parity over ALL the feature's ring segments (holes toggle;
    disjoint multipolygon members contribute even counts). Mirrors the
    accumulation in geom_numpy.points_in_polygon."""
    out = np.zeros(c, dtype=bool)
    s = len(segs)
    if s == 0 or len(lx) == 0:
        return out
    present, first = np.unique(seg_fid, return_index=True)
    x1, y1, x2, y2 = segs[:, 0], segs[:, 1], segs[:, 2], segs[:, 3]
    step = max(1, _CHUNK // s)
    for i in range(0, len(lx), step):
        pxv = lx[i:i + step, None]
        pyv = ly[i:i + step, None]
        cond = (y1 > pyv) != (y2 > pyv)
        with np.errstate(divide="ignore", invalid="ignore"):
            xint = (x2 - x1) * (pyv - y1) / (y2 - y1) + x1
        cross = cond & (pxv < xint)                       # (l, S)
        counts = np.add.reduceat(cross, first, axis=1)    # (l, |present|)
        out[present] |= np.any(counts % 2 == 1, axis=0)
    return out


def _segs_touch(segs: np.ndarray, seg_fid: np.ndarray, lsegs: np.ndarray,
                c: int, proper_only: bool = False) -> np.ndarray:
    """bool (c,): any feature segment crosses (or, proper_only, *properly*
    crosses) any literal segment. Orientation convention matches
    geom_numpy.segments_cross exactly."""
    out = np.zeros(c, dtype=bool)
    s, sl = len(segs), len(lsegs)
    if s == 0 or sl == 0:
        return out
    bx1, by1, bx2, by2 = (lsegs[:, j][None, :] for j in range(4))
    hit = np.zeros(s, dtype=bool)
    step = max(1, _CHUNK // sl)
    for i in range(0, s, step):
        a = segs[i:i + step]
        ax1, ay1, ax2, ay2 = (a[:, j][:, None] for j in range(4))
        d1 = (bx1 - ax1) * (ay2 - ay1) - (by1 - ay1) * (ax2 - ax1)
        d2 = (bx2 - ax1) * (ay2 - ay1) - (by2 - ay1) * (ax2 - ax1)
        d3 = (ax1 - bx1) * (by2 - by1) - (ay1 - by1) * (bx2 - bx1)
        d4 = (ax2 - bx1) * (by2 - by1) - (ay2 - by1) * (bx2 - bx1)
        # NB: orient(o, p, q) = (q-o) x (p-o) with the scalar convention
        # orient(ox,oy,px,py,qx,qy) = (px-ox)(qy-oy)-(py-oy)(qx-ox); the signs
        # above are its negation uniformly, which leaves sign-products intact.
        m = ((d1 * d2) < 0) & ((d3 * d4) < 0)
        if not proper_only:
            def on(ox, oy, qx, qy, px_, py_, d):
                return (d == 0) & (np.minimum(ox, qx) <= px_) \
                    & (px_ <= np.maximum(ox, qx)) \
                    & (np.minimum(oy, qy) <= py_) & (py_ <= np.maximum(oy, qy))
            m |= on(ax1, ay1, ax2, ay2, bx1, by1, d1) \
                | on(ax1, ay1, ax2, ay2, bx2, by2, d2) \
                | on(bx1, by1, bx2, by2, ax1, ay1, d3) \
                | on(bx1, by1, bx2, by2, ax2, ay2, d4)
        hit[i:i + step] = np.any(m, axis=1)
    return _any_per_feature(seg_fid, hit, c)


def _point_to_segs_min(coords: np.ndarray, fid: np.ndarray, lsegs: np.ndarray,
                       c: int) -> np.ndarray:
    """float (c,): min distance from any feature vertex to any literal seg."""
    if len(lsegs) == 0 or len(coords) == 0:
        return np.full(c, np.inf)
    step = max(1, _CHUNK // len(lsegs))
    dv = np.empty(len(coords))
    for i in range(0, len(coords), step):
        dv[i:i + step] = gn.point_segment_distance(
            coords[i:i + step, 0], coords[i:i + step, 1], lsegs)
    return _min_per_feature(fid, dv, c)


# -- public batched predicates ----------------------------------------------


def batch_intersects(arr: geo.GeometryArray, idx: np.ndarray,
                     literal: tuple, _soups=None) -> np.ndarray:
    """bool (len(idx),): exact-ish intersects per candidate feature,
    semantics identical to geom_numpy.geometry_intersects.

    ``_soups``: optional precomputed (coords, cfid, segs, sfid) for the same
    idx — batch_distance shares them to avoid rebuilding."""
    idx = np.asarray(idx, dtype=np.int64)
    c = len(idx)
    out = np.zeros(c, dtype=bool)
    if c == 0:
        return out
    lcode = literal[0]
    fcodes = arr.type_codes[idx]
    if _soups is None:
        coords, cfid = gather_coords(arr, idx)
        segs, sfid = build_segments(arr, idx)
    else:
        coords, cfid, segs, sfid = _soups
    lsegs = gn.literal_segments(literal)
    lc = gn.literal_coords(literal)

    # feature vertex inside polygonal literal (incl. boundary)
    if lcode in (geo.POLYGON, geo.MULTIPOLYGON):
        pip = _pip_chunked(coords[:, 0], coords[:, 1], literal)
        out |= _any_per_feature(cfid, pip, c)

    # literal vertex strictly inside polygonal feature (parity; the boundary
    # case is covered by the segment touch tests below)
    poly_feat = np.isin(fcodes, (geo.POLYGON, geo.MULTIPOLYGON))
    todo = poly_feat & ~out
    if np.any(todo):
        sub = np.nonzero(todo)[0]
        psegs, pfid = build_segments(arr, idx[sub])
        out[sub] |= _points_in_features(lc[:, 0], lc[:, 1], psegs, pfid,
                                        len(sub))

    # boundary segments touch
    out |= _segs_touch(segs, sfid, lsegs, c)

    # point-ish features / literals
    point_feat = np.isin(fcodes, (geo.POINT, geo.MULTIPOINT))
    if np.any(point_feat):
        pf = point_feat[cfid]
        if lcode in (geo.POINT, geo.MULTIPOINT):
            eq = _point_eq_chunked(coords, lc)
            out |= _any_per_feature(cfid, eq & pf, c)
        elif lcode in (geo.LINESTRING, geo.MULTILINESTRING):
            on = _on_segments_chunked(coords[:, 0], coords[:, 1], lsegs)
            out |= _any_per_feature(cfid, on & pf, c)
    if lcode in (geo.POINT, geo.MULTIPOINT) and len(segs):
        # literal vertex on a feature boundary segment
        seg_hit = _any_point_on_each_segment(lc, segs)
        out |= _any_per_feature(sfid, seg_hit, c)
    return out


def _any_point_on_each_segment(pts: np.ndarray, segs: np.ndarray,
                               eps: float = 1e-12) -> np.ndarray:
    """bool (S,): any of the points lies on each segment (same collinearity
    rule as geom_numpy._points_on_segments, reduced over points)."""
    s = len(segs)
    out = np.zeros(s, dtype=bool)
    if s == 0 or len(pts) == 0:
        return out
    px, py = pts[None, :, 0], pts[None, :, 1]
    step = max(1, _CHUNK // len(pts))
    for i in range(0, s, step):
        sub = segs[i:i + step]
        x1, y1 = sub[:, 0][:, None], sub[:, 1][:, None]
        x2, y2 = sub[:, 2][:, None], sub[:, 3][:, None]
        cross = (x2 - x1) * (py - y1) - (y2 - y1) * (px - x1)
        scale = np.maximum(np.abs(x2 - x1), np.abs(y2 - y1)) + eps
        collinear = np.abs(cross) <= eps * scale * np.maximum(
            1.0, np.maximum(np.abs(px), np.abs(py)))
        within = ((np.minimum(x1, x2) - eps <= px)
                  & (px <= np.maximum(x1, x2) + eps)
                  & (np.minimum(y1, y2) - eps <= py)
                  & (py <= np.maximum(y1, y2) + eps))
        out[i:i + step] = np.any(collinear & within, axis=1)
    return out


def batch_within(arr: geo.GeometryArray, idx: np.ndarray,
                 literal: tuple) -> np.ndarray:
    """bool (len(idx),): feature entirely within a polygonal literal —
    semantics identical to geom_numpy.geometry_within."""
    idx = np.asarray(idx, dtype=np.int64)
    c = len(idx)
    if c == 0:
        return np.zeros(0, dtype=bool)
    coords, cfid = gather_coords(arr, idx)
    pip = _pip_chunked(coords[:, 0], coords[:, 1], literal)
    all_in = np.bincount(cfid[~pip], minlength=c) == 0
    segs, sfid = build_segments(arr, idx)
    proper = _segs_touch(segs, sfid, gn.literal_segments(literal), c,
                         proper_only=True)
    return all_in & ~proper


def batch_distance(arr: geo.GeometryArray, idx: np.ndarray,
                   literal: tuple) -> np.ndarray:
    """float (len(idx),): approx min distance per candidate feature —
    semantics identical to geom_numpy.geometry_distance."""
    idx = np.asarray(idx, dtype=np.int64)
    c = len(idx)
    if c == 0:
        return np.zeros(0)
    coords, cfid = gather_coords(arr, idx)
    segs, sfid = build_segments(arr, idx)
    inter = batch_intersects(arr, idx, literal,
                             _soups=(coords, cfid, segs, sfid))
    lsegs = gn.literal_segments(literal)
    lc = gn.literal_coords(literal)
    d = np.full(c, np.inf)
    if len(lsegs):
        d = np.minimum(d, _point_to_segs_min(coords, cfid, lsegs, c))
    if len(segs):
        # literal vertices to feature segments: per-segment min over the
        # literal's vertices, then per-feature min
        step = max(1, _CHUNK // max(1, len(lc)))
        dm = np.empty(len(segs))
        for i in range(0, len(segs), step):
            sub = segs[i:i + step]
            dm[i:i + step] = _segs_to_points_min(sub, lc)
        d = np.minimum(d, _min_per_feature(sfid, dm, c))
    if not len(lsegs):
        # point-ish literal vs point-ish features: pure vertex distances
        has_segs = np.bincount(sfid, minlength=c) > 0 if len(segs) \
            else np.zeros(c, dtype=bool)
        nose = ~has_segs
        if np.any(nose):
            pv = nose[cfid]
            dv = _vertex_dist_chunked(coords[pv], lc)
            d = np.minimum(d, _min_per_feature(cfid[pv], dv, c))
    d[inter] = 0.0
    return d


def _segs_to_points_min(segs: np.ndarray, pts: np.ndarray) -> np.ndarray:
    """float (S,): min distance from each segment to any point."""
    x1, y1 = segs[:, 0][:, None], segs[:, 1][:, None]
    x2, y2 = segs[:, 2][:, None], segs[:, 3][:, None]
    px, py = pts[None, :, 0], pts[None, :, 1]
    dx, dy = x2 - x1, y2 - y1
    ll = dx * dx + dy * dy
    with np.errstate(divide="ignore", invalid="ignore"):
        t = np.clip(((px - x1) * dx + (py - y1) * dy)
                    / np.where(ll == 0, 1, ll), 0, 1)
    cx, cy = x1 + t * dx, y1 + t * dy
    return np.sqrt(np.min((px - cx) ** 2 + (py - cy) ** 2, axis=1))
