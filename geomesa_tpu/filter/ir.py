"""Predicate IR: the typed filter tree all layers share.

≙ the role GeoTools ``Filter`` objects play in the reference; GeoMesa compiles
them into fast evaluators (FastFilterFactory.scala) and extracts planning info
from them (FilterHelper.scala). Here the IR is a small algebra the parser
produces, the planner decomposes, and the numpy/jax backends evaluate.

Geometry literals are (type_code, nested-list) pairs as produced by
``features.geometry.parse_wkt``. Temporal literals are int64 epoch millis.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple


class Filter:
    """Base class; nodes are frozen dataclasses."""

    def __and__(self, other: "Filter") -> "Filter":
        return And([self, other])

    def __or__(self, other: "Filter") -> "Filter":
        return Or([self, other])

    def __invert__(self) -> "Filter":
        return Not(self)


@dataclass(frozen=True)
class Include(Filter):
    """Match everything (Filter.INCLUDE)."""


@dataclass(frozen=True)
class Exclude(Filter):
    """Match nothing (Filter.EXCLUDE)."""


@dataclass(frozen=True)
class And(Filter):
    children: Tuple[Filter, ...]

    def __init__(self, children: Sequence[Filter]):
        flat: List[Filter] = []
        for c in children:
            if isinstance(c, And):
                flat.extend(c.children)
            else:
                flat.append(c)
        object.__setattr__(self, "children", tuple(flat))


@dataclass(frozen=True)
class Or(Filter):
    children: Tuple[Filter, ...]

    def __init__(self, children: Sequence[Filter]):
        flat: List[Filter] = []
        for c in children:
            if isinstance(c, Or):
                flat.extend(c.children)
            else:
                flat.append(c)
        object.__setattr__(self, "children", tuple(flat))


@dataclass(frozen=True)
class Not(Filter):
    child: Filter


# -- spatial ----------------------------------------------------------------

@dataclass(frozen=True)
class BBox(Filter):
    attr: str
    xmin: float
    ymin: float
    xmax: float
    ymax: float


@dataclass(frozen=True)
class Intersects(Filter):
    attr: str
    geometry: tuple  # (type_code, nested lists)


@dataclass(frozen=True)
class Contains(Filter):
    """Literal geometry CONTAINS the feature geometry."""
    attr: str
    geometry: tuple


@dataclass(frozen=True)
class Within(Filter):
    """Feature geometry WITHIN the literal geometry."""
    attr: str
    geometry: tuple


@dataclass(frozen=True)
class Dwithin(Filter):
    attr: str
    geometry: tuple
    distance: float  # degrees


# -- geometry function calls (≙ geomesa-spark-jts st_* UDFs) ----------------

# canonical (lowercase) catalog names by kind
FUNC_BOOLEAN = frozenset({"st_contains", "st_intersects"})
FUNC_SCALAR = frozenset({"st_area", "st_length", "st_distance"})
FUNC_GEOM = frozenset({"st_buffer", "st_centroid", "st_convexhull"})
FUNC_NAMES = FUNC_BOOLEAN | FUNC_SCALAR | FUNC_GEOM


@dataclass(frozen=True)
class FuncExpr:
    """A geometry-valued st_* expression (st_buffer/st_centroid/
    st_convexHull) nested inside a predicate or projection — not itself a
    filter. Each arg is an attribute name (str), a geometry literal
    ``(type_code, nested lists)``, a float scalar, or a nested FuncExpr."""

    name: str     # canonical lowercase
    args: tuple


@dataclass(frozen=True)
class Func(Filter):
    """Boolean st_* predicate call: st_contains(a, b) / st_intersects(a, b).
    Args as in FuncExpr."""

    name: str
    args: tuple


@dataclass(frozen=True)
class FuncCmp(Filter):
    """Scalar st_* call compared to a literal:
    ``st_distance(geom, POINT(..)) < 5000``. op in {'=','<>','<','<=','>',
    '>='}; args as in FuncExpr."""

    op: str
    name: str
    args: tuple
    value: float


def _func_arg_attrs(args: tuple, out: set) -> None:
    for a in args:
        if isinstance(a, str):
            out.add(a)
        elif isinstance(a, FuncExpr):
            _func_arg_attrs(a.args, out)


def funcs_of(f: Filter) -> Tuple[str, ...]:
    """Sorted distinct st_* function names referenced anywhere in the tree
    (the workload plane's ``funcs`` flight dimension)."""
    out: set = set()

    def walk_args(args: tuple) -> None:
        for a in args:
            if isinstance(a, FuncExpr):
                out.add(a.name)
                walk_args(a.args)

    def walk(f: Filter) -> None:
        if isinstance(f, Not):
            walk(f.child)
        elif isinstance(f, (And, Or)):
            for c in f.children:
                walk(c)
        elif isinstance(f, (Func, FuncCmp)):
            out.add(f.name)
            walk_args(f.args)

    walk(f)
    return tuple(sorted(out))


# -- temporal ---------------------------------------------------------------

@dataclass(frozen=True)
class During(Filter):
    """attr in (lo, hi); ECQL DURING is exclusive on both ends, BETWEEN is
    inclusive — modeled with the *_inclusive flags."""

    attr: str
    lo: int   # epoch millis
    hi: int
    lo_inclusive: bool = False
    hi_inclusive: bool = False


# -- attribute --------------------------------------------------------------

@dataclass(frozen=True)
class Cmp(Filter):
    """Property comparison: op in {'=', '<>', '<', '<=', '>', '>='}."""

    op: str
    attr: str
    value: object


@dataclass(frozen=True)
class In(Filter):
    attr: str
    values: Tuple[object, ...]


@dataclass(frozen=True)
class IsNull(Filter):
    attr: str


@dataclass(frozen=True)
class FidFilter(Filter):
    """Feature-id lookup (ECQL ``IN ('fid1', ...)`` with no attribute)."""

    fids: Tuple[str, ...]


def and_filters(filters: Sequence[Filter]) -> Filter:
    """Combine, dropping INCLUDEs (reference filter/package.scala andFilters)."""
    fs = [f for f in filters if not isinstance(f, Include)]
    if not fs:
        return Include()
    if any(isinstance(f, Exclude) for f in fs):
        return Exclude()
    return fs[0] if len(fs) == 1 else And(fs)


def or_filters(filters: Sequence[Filter]) -> Filter:
    fs = [f for f in filters if not isinstance(f, Exclude)]
    if not fs:
        return Exclude()
    if any(isinstance(f, Include) for f in fs):
        return Include()
    return fs[0] if len(fs) == 1 else Or(fs)


def attributes_of(f: Filter) -> Optional[set]:
    """Attribute names a filter references, or None when it needs more than
    attribute columns (fid filters read the fid sidecar). Drives projection
    push-down: a columnar reader can hydrate only these columns to evaluate
    the filter (≙ the reference's ArrowFilterOptimizer / ORC column pruning,
    OrcFileSystemStorage's read schemas)."""
    if isinstance(f, (Include, Exclude)):
        return set()
    if isinstance(f, FidFilter):
        return None
    if isinstance(f, Not):
        return attributes_of(f.child)
    if isinstance(f, (And, Or)):
        out: set = set()
        for c in f.children:
            sub = attributes_of(c)
            if sub is None:
                return None
            out |= sub
        return out
    if isinstance(f, (Func, FuncCmp)):
        out = set()
        _func_arg_attrs(f.args, out)
        return out
    attr = getattr(f, "attr", None)
    return {attr} if attr is not None else None
