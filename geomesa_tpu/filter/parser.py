"""Recursive-descent parser for the ECQL subset the framework accepts.

≙ the reference's use of GeoTools ``ECQL.toFilter``. Grammar:

  expr        := or_expr
  or_expr     := and_expr (OR and_expr)*
  and_expr    := not_expr (AND not_expr)*
  not_expr    := NOT not_expr | '(' expr ')' | predicate
  predicate   := INCLUDE | EXCLUDE
               | BBOX '(' attr ',' num ',' num ',' num ',' num ')'
               | INTERSECTS|CONTAINS|WITHIN '(' attr ',' wkt ')'
               | DWITHIN '(' attr ',' wkt ',' num ',' units ')'
               | ST_CONTAINS|ST_INTERSECTS '(' farg ',' farg ')'
               | ST_AREA|ST_LENGTH|ST_DISTANCE '(' farg* ')' op num
               | attr DURING iso '/' iso
               | attr BETWEEN lit AND lit
               | attr IN '(' lit (',' lit)* ')'
               | IN '(' str (',' str)* ')'          -- fid filter
               | attr IS [NOT] NULL
               | attr ('='|'<>'|'<='|'>='|'<'|'>') lit

Dates parse to int64 epoch millis; strings are single-quoted.

Geometry function calls (≙ geomesa-spark-jts UDFs, case-insensitive):
``farg`` is an attribute, a WKT literal, a number, or a nested geometry
function (st_buffer/st_centroid/st_convexHull). Boolean calls
(st_contains/st_intersects) stand alone as predicates; scalar calls
(st_area/st_length/st_distance) must be compared to a number, e.g.
``st_distance(geom, POINT(10 20)) < 0.5 AND st_contains(POLYGON(..), geom)``.
"""

from __future__ import annotations

import re
from typing import List, Optional

import numpy as np

from geomesa_tpu.features.geometry import parse_wkt
from geomesa_tpu.filter import ir

_TOKEN_RE = re.compile(
    r"""\s*(?:
        (?P<lparen>\() | (?P<rparen>\)) | (?P<comma>,) |
        (?P<op><=|>=|<>|=|<|>) |
        (?P<string>'(?:[^']|'')*') |
        (?P<datetime>\d{4}-\d{2}-\d{2}T[\d:.]+Z?) |
        (?P<number>-?\d+\.?\d*(?:[eE][+-]?\d+)?) |
        (?P<slash>/) |
        (?P<word>[A-Za-z_][A-Za-z0-9_.:]*)
    )""",
    re.VERBOSE,
)

_KEYWORDS = {
    "AND", "OR", "NOT", "INCLUDE", "EXCLUDE", "BBOX", "INTERSECTS", "CONTAINS",
    "WITHIN", "DWITHIN", "DURING", "BETWEEN", "IN", "IS", "NULL", "LIKE",
    "POINT", "LINESTRING", "POLYGON", "MULTIPOINT", "MULTILINESTRING",
    "MULTIPOLYGON", "TRUE", "FALSE",
}

_GEOM_WORDS = {"POINT", "LINESTRING", "POLYGON", "MULTIPOINT", "MULTILINESTRING", "MULTIPOLYGON"}


def _parse_dt(s: str) -> int:
    s = s.rstrip("Z")
    return int(np.datetime64(s, "ms").astype(np.int64))


class _Tokens:
    def __init__(self, text: str):
        self.text = text
        self.toks: List[tuple] = []
        pos = 0
        while pos < len(text):
            m = _TOKEN_RE.match(text, pos)
            if not m or m.end() == pos:
                if text[pos:].strip():
                    raise ValueError(f"Cannot tokenize ECQL at: {text[pos:pos+40]!r}")
                break
            pos = m.end()
            kind = m.lastgroup
            self.toks.append((kind, m.group(kind)))
        self.i = 0

    def peek(self, ahead: int = 0) -> Optional[tuple]:
        j = self.i + ahead
        return self.toks[j] if j < len(self.toks) else None

    def next(self) -> tuple:
        tok = self.peek()
        if tok is None:
            raise ValueError("Unexpected end of ECQL")
        self.i += 1
        return tok

    def expect(self, kind: str, value: Optional[str] = None) -> str:
        k, v = self.next()
        if k != kind or (value is not None and v.upper() != value):
            raise ValueError(f"Expected {value or kind}, got {v!r} in {self.text!r}")
        return v

    def peek_word(self) -> Optional[str]:
        tok = self.peek()
        return tok[1].upper() if tok and tok[0] == "word" else None


def parse_ecql(text: str) -> ir.Filter:
    if not text or not text.strip():
        return ir.Include()
    toks = _Tokens(text)
    f = _parse_or(toks)
    if toks.peek() is not None:
        raise ValueError(f"Trailing input in ECQL: {toks.peek()}")
    return f


def _parse_or(toks: _Tokens) -> ir.Filter:
    parts = [_parse_and(toks)]
    while toks.peek_word() == "OR":
        toks.next()
        parts.append(_parse_and(toks))
    return parts[0] if len(parts) == 1 else ir.Or(parts)


def _parse_and(toks: _Tokens) -> ir.Filter:
    parts = [_parse_not(toks)]
    while toks.peek_word() == "AND":
        toks.next()
        parts.append(_parse_not(toks))
    return parts[0] if len(parts) == 1 else ir.And(parts)


def _parse_not(toks: _Tokens) -> ir.Filter:
    if toks.peek_word() == "NOT":
        toks.next()
        return ir.Not(_parse_not(toks))
    tok = toks.peek()
    if tok and tok[0] == "lparen":
        # could be a parenthesized expression
        toks.next()
        f = _parse_or(toks)
        toks.expect("rparen")
        return f
    return _parse_predicate(toks)


def _parse_wkt_literal(toks: _Tokens) -> tuple:
    word = toks.expect("word").upper()
    if word not in _GEOM_WORDS:
        raise ValueError(f"Expected geometry literal, got {word}")
    # re-assemble the parenthesized coordinate text
    depth = 0
    parts = [word]
    while True:
        k, v = toks.next()
        if k == "lparen":
            depth += 1
            parts.append("(")
        elif k == "rparen":
            depth -= 1
            parts.append(")")
            if depth == 0:
                break
        elif k == "comma":
            parts.append(",")
        else:
            parts.append(" " + v + " ")
    return parse_wkt("".join(parts))


def _parse_literal(toks: _Tokens):
    k, v = toks.next()
    if k == "string":
        return v[1:-1].replace("''", "'")
    if k == "number":
        return float(v) if ("." in v or "e" in v or "E" in v) else int(v)
    if k == "datetime":
        return _parse_dt(v)
    if k == "word" and v.upper() in ("TRUE", "FALSE"):
        return v.upper() == "TRUE"
    raise ValueError(f"Expected literal, got {v!r}")


def _parse_func_args(toks: _Tokens) -> tuple:
    """Comma-separated function arguments inside (already-consumed) parens:
    attribute names, WKT literals, numbers, or nested st_* calls."""
    toks.expect("lparen")
    args = []
    while True:
        tok = toks.peek()
        if tok is None:
            raise ValueError("Unterminated function call")
        k, v = tok
        if k == "word" and v.upper() in _GEOM_WORDS:
            args.append(_parse_wkt_literal(toks))
        elif k == "word" and v.lower() in ir.FUNC_NAMES:
            name = v.lower()
            if name not in ir.FUNC_GEOM:
                raise ValueError(
                    f"{v} does not return a geometry; only "
                    "st_buffer/st_centroid/st_convexHull nest")
            toks.next()
            args.append(ir.FuncExpr(name, _parse_func_args(toks)))
        elif k == "word":
            args.append(toks.next()[1])   # attribute reference
        elif k == "number":
            args.append(float(toks.next()[1]))
        else:
            raise ValueError(f"Bad function argument {v!r}")
        k2, _ = toks.next()
        if k2 == "rparen":
            return tuple(args)
        if k2 != "comma":
            raise ValueError(f"Expected ',' or ')' in function call, got {k2}")


def _parse_func_predicate(toks: _Tokens) -> ir.Filter:
    name = toks.expect("word").lower()
    args = _parse_func_args(toks)
    nxt = toks.peek()
    if nxt is not None and nxt[0] == "op":
        op = toks.next()[1]
        val = _parse_literal(toks)
        if not isinstance(val, (int, float)) or isinstance(val, bool):
            raise ValueError(f"{name} compares to a number, got {val!r}")
        if name not in ir.FUNC_SCALAR:
            raise ValueError(f"{name} is not numeric; only "
                             "st_area/st_length/st_distance compare")
        return ir.FuncCmp(op, name, args, float(val))
    if name in ir.FUNC_BOOLEAN:
        return ir.Func(name, args)
    raise ValueError(
        f"{name} is not a boolean predicate: compare it to a value "
        "(e.g. st_distance(geom, POINT(0 0)) < 1)")


def _parse_predicate(toks: _Tokens) -> ir.Filter:
    word = toks.peek_word()
    if word is None:
        raise ValueError(f"Expected predicate at token {toks.peek()}")

    if word == "INCLUDE":
        toks.next()
        return ir.Include()
    if word == "EXCLUDE":
        toks.next()
        return ir.Exclude()

    if word == "BBOX":
        toks.next()
        toks.expect("lparen")
        attr = toks.expect("word")
        vals = []
        for _ in range(4):
            toks.expect("comma")
            vals.append(float(toks.expect("number")))
        # optional trailing CRS argument
        if toks.peek() and toks.peek()[0] == "comma":
            toks.next()
            toks.next()
        toks.expect("rparen")
        return ir.BBox(attr, *vals)

    if word in ("INTERSECTS", "CONTAINS", "WITHIN"):
        toks.next()
        toks.expect("lparen")
        attr = toks.expect("word")
        toks.expect("comma")
        geom = _parse_wkt_literal(toks)
        toks.expect("rparen")
        cls = {"INTERSECTS": ir.Intersects, "CONTAINS": ir.Contains, "WITHIN": ir.Within}[word]
        return cls(attr, geom)

    if word == "DWITHIN":
        toks.next()
        toks.expect("lparen")
        attr = toks.expect("word")
        toks.expect("comma")
        geom = _parse_wkt_literal(toks)
        toks.expect("comma")
        dist = float(toks.expect("number"))
        if toks.peek() and toks.peek()[0] == "comma":  # units word (ignored: degrees)
            toks.next()
            toks.next()
        toks.expect("rparen")
        return ir.Dwithin(attr, geom, dist)

    if word.lower() in ir.FUNC_NAMES:
        return _parse_func_predicate(toks)

    if word == "IN":
        # bare IN(...) = feature-id filter
        toks.next()
        toks.expect("lparen")
        fids = [str(_parse_literal(toks))]
        while toks.peek() and toks.peek()[0] == "comma":
            toks.next()
            fids.append(str(_parse_literal(toks)))
        toks.expect("rparen")
        return ir.FidFilter(tuple(fids))

    # attribute-led predicates
    attr = toks.expect("word")
    nxt = toks.peek()
    if nxt is None:
        raise ValueError(f"Dangling attribute {attr!r}")

    if nxt[0] == "word":
        kw = nxt[1].upper()
        if kw == "DURING":
            toks.next()
            lo = _parse_dt(toks.expect("datetime"))
            toks.expect("slash")
            hi = _parse_dt(toks.expect("datetime"))
            return ir.During(attr, lo, hi)
        if kw == "BETWEEN":
            toks.next()
            lo = _parse_literal(toks)
            toks.expect("word", "AND")
            hi = _parse_literal(toks)
            if isinstance(lo, int) and isinstance(hi, int) and abs(hi) > 10**11:
                return ir.During(attr, lo, hi, True, True)
            return ir.And([ir.Cmp(">=", attr, lo), ir.Cmp("<=", attr, hi)])
        if kw == "IN":
            toks.next()
            toks.expect("lparen")
            vals = [_parse_literal(toks)]
            while toks.peek() and toks.peek()[0] == "comma":
                toks.next()
                vals.append(_parse_literal(toks))
            toks.expect("rparen")
            return ir.In(attr, tuple(vals))
        if kw == "IS":
            toks.next()
            negate = False
            if toks.peek_word() == "NOT":
                toks.next()
                negate = True
            toks.expect("word", "NULL")
            f: ir.Filter = ir.IsNull(attr)
            return ir.Not(f) if negate else f
        raise ValueError(f"Unsupported predicate keyword {kw!r}")

    if nxt[0] == "op":
        op = toks.next()[1]
        val = _parse_literal(toks)
        return ir.Cmp(op, attr, val)

    raise ValueError(f"Cannot parse predicate after {attr!r}: {nxt}")
