"""Filter layer (≙ reference geomesa-filter, SURVEY.md §2.2).

A small CQL/ECQL subset compiles to a typed predicate IR:

  - ``ir``       — predicate nodes (BBox, Intersects, During, Cmp, And/Or/Not…)
  - ``parser``   — ECQL text → IR
  - ``evaluate`` — host numpy evaluation (the brute-force / fallback path)
  - ``extract``  — FilterHelper-equivalents: pull bboxes/intervals for planning
  - ``compile``  — IR → jax mask kernel over device columns (the push-down
                   path, ≙ HBase filters / Accumulo iterators)
"""

from geomesa_tpu.filter.ir import (
    And, BBox, Cmp, Contains, During, Dwithin, Exclude, FidFilter, Include,
    Intersects, Not, Or, Within, Filter,
)
from geomesa_tpu.filter.parser import parse_ecql
from geomesa_tpu.filter.evaluate import evaluate
from geomesa_tpu.filter.extract import extract_bboxes, extract_intervals

__all__ = [
    "And", "BBox", "Cmp", "Contains", "During", "Dwithin", "Exclude",
    "FidFilter", "Include", "Intersects", "Not", "Or", "Within", "Filter",
    "parse_ecql", "evaluate", "extract_bboxes", "extract_intervals",
]
