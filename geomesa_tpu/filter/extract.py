"""Planning-time extraction: pull spatial bounds and temporal intervals out of
a filter tree.

≙ reference ``FilterHelper.extractGeometries`` / ``extractIntervals``
(/root/reference/geomesa-filter/.../FilterHelper.scala:101,147): traverse the
tree; AND intersects constraints, OR unions them. Returns disjunctive lists —
a list of bboxes / intervals whose union covers the constraint — plus a flag
marking whether extraction was exact (so the planner knows if the primary
constraint fully subsumes the predicate or a residual filter must run,
the useFullFilter decision).

Bboxes are clamped to the whole world; antimeridian-crossing boxes (xmin >
xmax) split into two, mirroring FilterHelper's normalization.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Tuple

import numpy as np

from geomesa_tpu.filter import geom_numpy as gn
from geomesa_tpu.filter import ir

WHOLE_WORLD = (-180.0, -90.0, 180.0, 90.0)
# unbounded interval sentinel (epoch millis)
MIN_MS = 0
MAX_MS = np.iinfo(np.int64).max // 2


@dataclass(frozen=True)
class Extraction:
    """Disjoint union of boxes/intervals covering the filter's constraint.

    ``exact`` — True when the union *is* the constraint (e.g. a single BBOX),
    False when it over-covers (e.g. bbox of a polygon intersects). Drives the
    useFullFilter decision (Z3IndexKeySpace.scala:235-249).
    """

    boxes: Tuple[Tuple[float, float, float, float], ...]
    exact: bool

    @property
    def unconstrained(self) -> bool:
        return len(self.boxes) == 1 and self.boxes[0] == WHOLE_WORLD


def _clamp_box(b: Tuple[float, float, float, float]) -> List[Tuple[float, float, float, float]]:
    xmin, ymin, xmax, ymax = b
    ymin = max(ymin, -90.0)
    ymax = min(ymax, 90.0)
    if xmin > xmax:  # antimeridian crossing: split
        return [(max(xmin, -180.0), ymin, 180.0, ymax), (-180.0, ymin, min(xmax, 180.0), ymax)]
    return [(max(xmin, -180.0), ymin, min(xmax, 180.0), ymax)]


def _intersect_boxes(a, b):
    out = []
    for ax0, ay0, ax1, ay1 in a:
        for bx0, by0, bx1, by1 in b:
            x0, y0 = max(ax0, bx0), max(ay0, by0)
            x1, y1 = min(ax1, bx1), min(ay1, by1)
            if x0 <= x1 and y0 <= y1:
                out.append((x0, y0, x1, y1))
    return out


def extract_bboxes(f: ir.Filter, attr: Optional[str] = None) -> Extraction:
    """Spatial constraint of ``f`` on geometry attribute ``attr`` (None = any)."""

    def walk(node: ir.Filter) -> Tuple[List[Tuple[float, float, float, float]], bool]:
        if isinstance(node, ir.BBox) and (attr is None or node.attr == attr):
            return _clamp_box((node.xmin, node.ymin, node.xmax, node.ymax)), True
        if isinstance(node, (ir.Intersects, ir.Contains, ir.Within)) and \
                (attr is None or node.attr == attr):
            box = gn.literal_bbox(node.geometry)
            from geomesa_tpu.features import geometry as geo
            # a bbox-shaped polygon (axis-aligned rectangle) extracts exactly
            exact = node.geometry[0] == geo.POINT or _is_rectangle(node.geometry)
            return _clamp_box(box), exact and isinstance(node, ir.Intersects)
        if isinstance(node, ir.Dwithin) and (attr is None or node.attr == attr):
            x0, y0, x1, y1 = gn.literal_bbox(node.geometry)
            d = node.distance
            return _clamp_box((x0 - d, y0 - d, x1 + d, y1 + d)), False
        if isinstance(node, (ir.Func, ir.FuncCmp)):
            box = _func_box(node, attr)
            if box is not None:
                return _clamp_box(box), False   # always loose: host refines
            return None, True
        if isinstance(node, ir.And):
            exact = True
            constrained = False
            acc = list(_clamp_box(WHOLE_WORLD))
            for c in node.children:
                cb, ce = walk(c)
                if cb is None:
                    continue
                acc = _intersect_boxes(acc, cb)
                exact = exact and ce
                constrained = True
            if not constrained:
                return None, True
            return acc, exact
        if isinstance(node, ir.Or):
            boxes = []
            exact = True
            for c in node.children:
                cb, ce = walk(c)
                if cb is None:
                    return None, True  # one branch unconstrained -> whole world
                boxes.extend(cb)
                exact = exact and ce
            return boxes, exact
        if isinstance(node, ir.Not):
            return None, False  # negations don't constrain the scan
        return None, True  # non-spatial predicate: no constraint

    boxes, exact = walk(f)
    if boxes is None:
        return Extraction((WHOLE_WORLD,), False)
    if not boxes:
        return Extraction((), True)  # spatially unsatisfiable
    return Extraction(tuple(boxes), exact)


def _func_box(node, attr: Optional[str]
              ) -> Optional[Tuple[float, float, float, float]]:
    """Sound spatial constraint of a geometry-function predicate on ``attr``:
    st_contains/st_intersects of the raw attribute vs a constant literal
    constrain to the literal's bbox; st_distance(attr, lit) < d expands it
    by d. Everything else (nested exprs, attr-vs-attr) is unconstrained."""
    args = node.args
    attr_arg = lit = None
    for a in args:
        if isinstance(a, str):
            attr_arg = a
        elif isinstance(a, tuple):
            lit = a
    if attr_arg is None or lit is None or len(args) != 2:
        return None
    if attr is not None and attr_arg != attr:
        return None
    if isinstance(node, ir.Func):
        return gn.literal_bbox(lit)
    if node.name == "st_distance" and node.op in ("<", "<="):
        d = max(float(node.value), 0.0)
        x0, y0, x1, y1 = gn.literal_bbox(lit)
        return (x0 - d, y0 - d, x1 + d, y1 + d)
    return None


def _is_rectangle(literal: tuple) -> bool:
    from geomesa_tpu.features import geometry as geo
    code, data = literal
    if code != geo.POLYGON or len(data) != 1:
        return False
    ring = np.asarray(data[0], dtype=np.float64)
    if np.array_equal(ring[0], ring[-1]):
        ring = ring[:-1]
    if len(ring) != 4:
        return False
    xs, ys = sorted(set(ring[:, 0])), sorted(set(ring[:, 1]))
    return len(xs) == 2 and len(ys) == 2


@dataclass(frozen=True)
class IntervalExtraction:
    intervals: Tuple[Tuple[int, int], ...]  # inclusive millis [lo, hi]
    exact: bool

    @property
    def unconstrained(self) -> bool:
        return len(self.intervals) == 1 and self.intervals[0] == (MIN_MS, MAX_MS)


def _intersect_intervals(a, b):
    out = []
    for alo, ahi in a:
        for blo, bhi in b:
            lo, hi = max(alo, blo), min(ahi, bhi)
            if lo <= hi:
                out.append((lo, hi))
    return out


def extract_intervals(f: ir.Filter, attr: str) -> IntervalExtraction:
    """Temporal constraint on ``attr`` as inclusive millis intervals.

    Exclusive DURING endpoints tighten by 1ms (the key offset resolution),
    mirroring how the reference converts to indexable bounds
    (BinnedTime.boundsToIndexableDates).
    """

    def walk(node: ir.Filter):
        if isinstance(node, ir.During) and node.attr == attr:
            lo = node.lo if node.lo_inclusive else node.lo + 1
            hi = node.hi if node.hi_inclusive else node.hi - 1
            return ([(lo, hi)] if lo <= hi else []), True
        if isinstance(node, ir.Cmp) and node.attr == attr and isinstance(node.value, (int, np.integer)):
            v = int(node.value)
            if node.op == "=":
                return [(v, v)], True
            if node.op == "<":
                return [(MIN_MS, v - 1)], True
            if node.op == "<=":
                return [(MIN_MS, v)], True
            if node.op == ">":
                return [(v + 1, MAX_MS)], True
            if node.op == ">=":
                return [(v, MAX_MS)], True
            return None, True
        if isinstance(node, ir.And):
            acc = [(MIN_MS, MAX_MS)]
            exact = True
            constrained = False
            for c in node.children:
                ci, ce = walk(c)
                if ci is None:
                    continue
                acc = _intersect_intervals(acc, ci)
                exact = exact and ce
                constrained = True
            return (acc if constrained else None), exact
        if isinstance(node, ir.Or):
            ivs = []
            exact = True
            for c in node.children:
                ci, ce = walk(c)
                if ci is None:
                    return None, True
                ivs.extend(ci)
                exact = exact and ce
            return ivs, exact
        if isinstance(node, ir.Not):
            return None, False
        return None, True

    ivs, exact = walk(f)
    if ivs is None:
        return IntervalExtraction(((MIN_MS, MAX_MS),), False)
    if not ivs:
        return IntervalExtraction((), True)
    # merge overlaps
    ivs = sorted(ivs)
    merged = [list(ivs[0])]
    for lo, hi in ivs[1:]:
        if lo <= merged[-1][1] + 1:
            merged[-1][1] = max(merged[-1][1], hi)
        else:
            merged.append([lo, hi])
    return IntervalExtraction(tuple((lo, hi) for lo, hi in merged), exact)
