"""Host numpy evaluation of the filter IR over a FeatureTable.

≙ the reference's client-side fallback evaluation path
(LocalQueryRunner.scala:49 — filter → visibility → transform chain, minus
visibility), and the test oracle for all device kernels. ``evaluate`` returns
a boolean mask over the table's rows; ``evaluate_at`` evaluates only at the
given candidate rows (the residual-refine hot path: no sub-table
materialization, geometry predicates batched via ``geom_batch``).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from geomesa_tpu.features import geometry as geo
from geomesa_tpu.features.table import FeatureTable, StringColumn
from geomesa_tpu.filter import geom_batch as gb
from geomesa_tpu.filter import geom_numpy as gn
from geomesa_tpu.filter import ir


def evaluate(f: ir.Filter, table: FeatureTable) -> np.ndarray:
    """Boolean mask over all table rows."""
    return _eval(f, table, None)


def evaluate_at(f: ir.Filter, table: FeatureTable,
                rows: np.ndarray) -> np.ndarray:
    """Boolean mask over ``rows`` (indices into the table) — the refine path:
    evaluates in place, never materializing a sub-table."""
    return _eval(f, table, np.asarray(rows, dtype=np.int64))


def _nrows(table: FeatureTable, rows: Optional[np.ndarray]) -> int:
    return len(table) if rows is None else len(rows)


def _eval(f: ir.Filter, table: FeatureTable,
          rows: Optional[np.ndarray]) -> np.ndarray:
    n = _nrows(table, rows)
    if isinstance(f, ir.Include):
        return np.ones(n, dtype=bool)
    if isinstance(f, ir.Exclude):
        return np.zeros(n, dtype=bool)
    if isinstance(f, ir.And):
        mask = np.ones(n, dtype=bool)
        for c in f.children:
            mask &= _eval(c, table, rows)
        return mask
    if isinstance(f, ir.Or):
        mask = np.zeros(n, dtype=bool)
        for c in f.children:
            mask |= _eval(c, table, rows)
        return mask
    if isinstance(f, ir.Not):
        return ~_eval(f.child, table, rows)
    if isinstance(f, ir.BBox):
        return _bbox(f, table, rows)
    if isinstance(f, (ir.Intersects, ir.Contains, ir.Within)):
        return _spatial(f, table, rows)
    if isinstance(f, ir.Dwithin):
        return _dwithin(f, table, rows)
    if isinstance(f, ir.During):
        col = np.asarray(table.column(f.attr), dtype=np.int64)
        if rows is not None:
            col = col[rows]
        lo = (col >= f.lo) if f.lo_inclusive else (col > f.lo)
        hi = (col <= f.hi) if f.hi_inclusive else (col < f.hi)
        return lo & hi
    if isinstance(f, ir.Cmp):
        return _cmp(f, table, rows)
    if isinstance(f, ir.In):
        col = table.column(f.attr)
        if isinstance(col, StringColumn):
            codes = col.codes if rows is None else col.codes[rows]
            wanted = {v for v in f.values}
            keep = {i for i, v in enumerate(col.vocab) if v in wanted}
            return np.isin(codes, list(keep))
        arr = np.asarray(col) if rows is None else np.asarray(col)[rows]
        return np.isin(arr, list(f.values))
    if isinstance(f, ir.IsNull):
        col = table.column(f.attr)
        if isinstance(col, StringColumn):
            codes = col.codes if rows is None else col.codes[rows]
            return np.array([col.vocab[c] == "" for c in codes])
        arr = np.asarray(col) if rows is None else np.asarray(col)[rows]
        return np.isnan(arr) if arr.dtype.kind == "f" else np.zeros(len(arr), dtype=bool)
    if isinstance(f, ir.FidFilter):
        wanted = set(f.fids)
        fids = table.fids if rows is None else table.fids_at(rows)
        return np.array([fid in wanted for fid in fids], dtype=bool)
    if isinstance(f, (ir.Func, ir.FuncCmp)):
        # host-oracle backend only: this evaluator IS the parity reference
        # for the device catalog, so it must never route through it
        from geomesa_tpu.geom.functions import eval_filter_node
        return eval_filter_node(f, table, rows, kernels=False)
    raise NotImplementedError(f"Cannot evaluate {type(f).__name__}")


def _geom_col(table: FeatureTable, attr: str) -> geo.GeometryArray:
    col = table.column(attr)
    if not isinstance(col, geo.GeometryArray):
        raise TypeError(f"Attribute {attr} is not a geometry")
    return col


def _bbox(f: ir.BBox, table: FeatureTable,
          rows: Optional[np.ndarray]) -> np.ndarray:
    """Envelope-overlap semantics (the reference's loose-bbox behavior, exact
    for points — Z3IndexKeySpace.useFullFilter:235-249 discussion)."""
    arr = _geom_col(table, f.attr)
    bb = arr.bboxes()
    if rows is not None:
        bb = bb[rows]
    return (
        (bb[:, 0] <= f.xmax) & (bb[:, 2] >= f.xmin)
        & (bb[:, 1] <= f.ymax) & (bb[:, 3] >= f.ymin)
    )


def _spatial(f, table: FeatureTable,
             rows: Optional[np.ndarray]) -> np.ndarray:
    arr = _geom_col(table, f.attr)
    lit = f.geometry
    n = _nrows(table, rows)
    out = np.zeros(n, dtype=bool)
    # bbox prefilter
    lx0, ly0, lx1, ly1 = gn.literal_bbox(lit)
    bb = arr.bboxes()
    if rows is not None:
        bb = bb[rows]
    cand = np.nonzero(
        (bb[:, 0] <= lx1) & (bb[:, 2] >= lx0) & (bb[:, 1] <= ly1) & (bb[:, 3] >= ly0))[0]
    if len(cand) == 0:
        return out
    cand_rows = cand if rows is None else rows[cand]
    if arr.is_points and lit[0] in (geo.POLYGON, geo.MULTIPOLYGON):
        # vectorized fast path for point layers
        x, y = arr.point_xy()
        out[cand] = gn.points_in_polygon(x[cand_rows], y[cand_rows], lit)
        return out
    if isinstance(f, ir.Intersects):
        out[cand] = gb.batch_intersects(arr, cand_rows, lit)
    else:
        # Within: feature within literal; Contains: literal contains
        # feature — same relation from the feature's perspective
        out[cand] = gb.batch_within(arr, cand_rows, lit)
    return out


def _dwithin(f: ir.Dwithin, table: FeatureTable,
             rows: Optional[np.ndarray]) -> np.ndarray:
    arr = _geom_col(table, f.attr)
    n = _nrows(table, rows)
    out = np.zeros(n, dtype=bool)
    lx0, ly0, lx1, ly1 = gn.literal_bbox(f.geometry)
    d = f.distance
    bb = arr.bboxes()
    if rows is not None:
        bb = bb[rows]
    cand = np.nonzero(
        (bb[:, 0] <= lx1 + d) & (bb[:, 2] >= lx0 - d)
        & (bb[:, 1] <= ly1 + d) & (bb[:, 3] >= ly0 - d))[0]
    if len(cand) == 0:
        return out
    cand_rows = cand if rows is None else rows[cand]
    if arr.is_points and f.geometry[0] in (geo.POLYGON, geo.MULTIPOLYGON,
                                           geo.LINESTRING, geo.MULTILINESTRING):
        x, y = arr.point_xy()
        inside = gn.points_in_polygon(x[cand_rows], y[cand_rows], f.geometry) \
            if f.geometry[0] in (geo.POLYGON, geo.MULTIPOLYGON) \
            else np.zeros(len(cand), bool)
        dist = gn.point_segment_distance(x[cand_rows], y[cand_rows],
                                         gn.literal_segments(f.geometry))
        out[cand] = inside | (dist <= d)
        return out
    out[cand] = gb.batch_distance(arr, cand_rows, f.geometry) <= d
    return out


def _cmp(f: ir.Cmp, table: FeatureTable,
         rows: Optional[np.ndarray]) -> np.ndarray:
    col = table.column(f.attr)
    if isinstance(col, StringColumn):
        codes = col.codes if rows is None else col.codes[rows]
        if f.op in ("=", "<>"):
            try:
                code = col.vocab.index(f.value)
                mask = codes == code
            except ValueError:
                mask = np.zeros(len(codes), dtype=bool)
            return mask if f.op == "=" else ~mask
        # ordered string comparison against the vocab
        vals = np.array(col.vocab, dtype=object)[codes]
        return _apply_op(f.op, vals, f.value)
    arr = np.asarray(col) if rows is None else np.asarray(col)[rows]
    return _apply_op(f.op, arr, f.value)


def _apply_op(op: str, arr, value) -> np.ndarray:
    if op == "=":
        return arr == value
    if op == "<>":
        return arr != value
    if op == "<":
        return arr < value
    if op == "<=":
        return arr <= value
    if op == ">":
        return arr > value
    if op == ">=":
        return arr >= value
    raise ValueError(f"Unknown op {op}")
