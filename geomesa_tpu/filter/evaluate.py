"""Host numpy evaluation of the filter IR over a FeatureTable.

≙ the reference's client-side fallback evaluation path
(LocalQueryRunner.scala:49 — filter → visibility → transform chain, minus
visibility), and the test oracle for all device kernels. Returns a boolean
mask over the table's rows.
"""

from __future__ import annotations

import numpy as np

from geomesa_tpu.features import geometry as geo
from geomesa_tpu.features.table import FeatureTable, StringColumn
from geomesa_tpu.filter import geom_numpy as gn
from geomesa_tpu.filter import ir


def evaluate(f: ir.Filter, table: FeatureTable) -> np.ndarray:
    n = len(table)
    if isinstance(f, ir.Include):
        return np.ones(n, dtype=bool)
    if isinstance(f, ir.Exclude):
        return np.zeros(n, dtype=bool)
    if isinstance(f, ir.And):
        mask = np.ones(n, dtype=bool)
        for c in f.children:
            mask &= evaluate(c, table)
        return mask
    if isinstance(f, ir.Or):
        mask = np.zeros(n, dtype=bool)
        for c in f.children:
            mask |= evaluate(c, table)
        return mask
    if isinstance(f, ir.Not):
        return ~evaluate(f.child, table)
    if isinstance(f, ir.BBox):
        return _bbox(f, table)
    if isinstance(f, (ir.Intersects, ir.Contains, ir.Within)):
        return _spatial(f, table)
    if isinstance(f, ir.Dwithin):
        return _dwithin(f, table)
    if isinstance(f, ir.During):
        col = np.asarray(table.column(f.attr), dtype=np.int64)
        lo = (col >= f.lo) if f.lo_inclusive else (col > f.lo)
        hi = (col <= f.hi) if f.hi_inclusive else (col < f.hi)
        return lo & hi
    if isinstance(f, ir.Cmp):
        return _cmp(f, table)
    if isinstance(f, ir.In):
        col = table.column(f.attr)
        if isinstance(col, StringColumn):
            wanted = {v for v in f.values}
            codes = {i for i, v in enumerate(col.vocab) if v in wanted}
            return np.isin(col.codes, list(codes))
        return np.isin(np.asarray(col), list(f.values))
    if isinstance(f, ir.IsNull):
        col = table.column(f.attr)
        if isinstance(col, StringColumn):
            return np.array([col.vocab[c] == "" for c in col.codes])
        arr = np.asarray(col)
        return np.isnan(arr) if arr.dtype.kind == "f" else np.zeros(len(arr), dtype=bool)
    if isinstance(f, ir.FidFilter):
        wanted = set(f.fids)
        return np.array([fid in wanted for fid in table.fids], dtype=bool)
    raise NotImplementedError(f"Cannot evaluate {type(f).__name__}")


def _geom_col(table: FeatureTable, attr: str) -> geo.GeometryArray:
    col = table.column(attr)
    if not isinstance(col, geo.GeometryArray):
        raise TypeError(f"Attribute {attr} is not a geometry")
    return col


def _bbox(f: ir.BBox, table: FeatureTable) -> np.ndarray:
    """Envelope-overlap semantics (the reference's loose-bbox behavior, exact
    for points — Z3IndexKeySpace.useFullFilter:235-249 discussion)."""
    arr = _geom_col(table, f.attr)
    bb = arr.bboxes()
    return (
        (bb[:, 0] <= f.xmax) & (bb[:, 2] >= f.xmin)
        & (bb[:, 1] <= f.ymax) & (bb[:, 3] >= f.ymin)
    )


def _spatial(f, table: FeatureTable) -> np.ndarray:
    arr = _geom_col(table, f.attr)
    lit = f.geometry
    n = len(table)
    out = np.zeros(n, dtype=bool)
    # bbox prefilter
    lx0, ly0, lx1, ly1 = gn.literal_bbox(lit)
    bb = arr.bboxes()
    cand = np.nonzero(
        (bb[:, 0] <= lx1) & (bb[:, 2] >= lx0) & (bb[:, 1] <= ly1) & (bb[:, 3] >= ly0))[0]
    if len(cand) == 0:
        return out
    if arr.is_points and lit[0] in (geo.POLYGON, geo.MULTIPOLYGON):
        # vectorized fast path for point layers
        x, y = arr.point_xy()
        res = gn.points_in_polygon(x[cand], y[cand], lit)
        out[cand] = res
        return out
    for i in cand:
        if isinstance(f, ir.Intersects):
            out[i] = gn.geometry_intersects(arr, int(i), lit)
        elif isinstance(f, (ir.Within, ir.Contains)):
            # Within: feature within literal; Contains: literal contains
            # feature — same relation from the feature's perspective
            out[i] = gn.geometry_within(arr, int(i), lit)
    return out


def _dwithin(f: ir.Dwithin, table: FeatureTable) -> np.ndarray:
    arr = _geom_col(table, f.attr)
    n = len(table)
    out = np.zeros(n, dtype=bool)
    lx0, ly0, lx1, ly1 = gn.literal_bbox(f.geometry)
    d = f.distance
    bb = arr.bboxes()
    cand = np.nonzero(
        (bb[:, 0] <= lx1 + d) & (bb[:, 2] >= lx0 - d)
        & (bb[:, 1] <= ly1 + d) & (bb[:, 3] >= ly0 - d))[0]
    if arr.is_points and f.geometry[0] in (geo.POLYGON, geo.MULTIPOLYGON, geo.LINESTRING,
                                           geo.MULTILINESTRING):
        x, y = arr.point_xy()
        inside = gn.points_in_polygon(x[cand], y[cand], f.geometry) \
            if f.geometry[0] in (geo.POLYGON, geo.MULTIPOLYGON) else np.zeros(len(cand), bool)
        dist = gn.point_segment_distance(x[cand], y[cand], gn.literal_segments(f.geometry))
        out[cand] = inside | (dist <= d)
        return out
    for i in cand:
        out[i] = gn.geometry_distance(arr, int(i), f.geometry) <= d
    return out


def _cmp(f: ir.Cmp, table: FeatureTable) -> np.ndarray:
    col = table.column(f.attr)
    if isinstance(col, StringColumn):
        if f.op in ("=", "<>"):
            try:
                code = col.vocab.index(f.value)
                mask = col.codes == code
            except ValueError:
                mask = np.zeros(len(col), dtype=bool)
            return mask if f.op == "=" else ~mask
        # ordered string comparison against the vocab
        vals = np.array(col.vocab, dtype=object)[col.codes]
        return _apply_op(f.op, vals, f.value)
    arr = np.asarray(col)
    return _apply_op(f.op, arr, f.value)


def _apply_op(op: str, arr, value) -> np.ndarray:
    if op == "=":
        return arr == value
    if op == "<>":
        return arr != value
    if op == "<":
        return arr < value
    if op == "<=":
        return arr <= value
    if op == ">":
        return arr > value
    if op == ">=":
        return arr >= value
    raise ValueError(f"Unknown op {op}")
