"""Exact host-side geometry predicates (float64 numpy).

This is the framework's JTS-equivalent for the predicate surface the filters
need: point-in-polygon (crossing parity), segment intersection, distance.
It serves three roles:
  1. brute-force reference evaluation in tests (the SURVEY.md §4 property
     tests: query results == brute-force filter on random data)
  2. host-side refinement of candidates the loose device mask returns
     (≙ reference "useFullFilter" residual ECQL evaluation)
  3. preparation of padded vertex buffers for the device kernels

Geometry literals are (type_code, nested lists) as in features.geometry.
"""

from __future__ import annotations

from typing import List, Tuple

import numpy as np

from geomesa_tpu.features import geometry as geo


def polygon_rings(literal: tuple) -> List[np.ndarray]:
    """All rings of a Polygon/MultiPolygon literal as (k,2) closed arrays."""
    code, data = literal
    if code == geo.POLYGON:
        polys = [data]
    elif code == geo.MULTIPOLYGON:
        polys = data
    else:
        raise ValueError(f"Expected polygonal literal, got type {code}")
    rings = []
    for poly in polys:
        for ring in poly:
            arr = np.asarray(ring, dtype=np.float64)
            if not np.array_equal(arr[0], arr[-1]):
                arr = np.vstack([arr, arr[:1]])
            rings.append(arr)
    return rings


def literal_coords(literal: tuple) -> np.ndarray:
    """All coordinates of any literal as an (M, 2) array."""
    code, data = literal
    if code == geo.POINT:
        return np.asarray([data], dtype=np.float64)
    if code in (geo.LINESTRING, geo.MULTIPOINT):
        return np.asarray(data, dtype=np.float64)
    if code in (geo.POLYGON, geo.MULTILINESTRING):
        return np.concatenate([np.asarray(r, dtype=np.float64) for r in data])
    if code == geo.MULTIPOLYGON:
        return np.concatenate([np.asarray(r, dtype=np.float64) for p in data for r in p])
    raise ValueError(f"Unknown literal type {code}")


def literal_segments(literal: tuple) -> np.ndarray:
    """Boundary segments of a literal as (S, 4) [x1, y1, x2, y2]."""
    code, data = literal
    segs = []

    def ring_segs(ring, close: bool):
        arr = np.asarray(ring, dtype=np.float64)
        if close and not np.array_equal(arr[0], arr[-1]):
            arr = np.vstack([arr, arr[:1]])
        if len(arr) >= 2:
            segs.append(np.concatenate([arr[:-1], arr[1:]], axis=1))

    if code == geo.LINESTRING:
        ring_segs(data, close=False)
    elif code == geo.MULTILINESTRING:
        for line in data:
            ring_segs(line, close=False)
    elif code == geo.POLYGON:
        for ring in data:
            ring_segs(ring, close=True)
    elif code == geo.MULTIPOLYGON:
        for poly in data:
            for ring in poly:
                ring_segs(ring, close=True)
    elif code in (geo.POINT, geo.MULTIPOINT):
        return np.zeros((0, 4))
    else:
        raise ValueError(f"Unknown literal type {code}")
    return np.concatenate(segs) if segs else np.zeros((0, 4))


def literal_bbox(literal: tuple) -> Tuple[float, float, float, float]:
    c = literal_coords(literal)
    return float(c[:, 0].min()), float(c[:, 1].min()), float(c[:, 0].max()), float(c[:, 1].max())


def points_in_polygon(px: np.ndarray, py: np.ndarray, literal: tuple) -> np.ndarray:
    """Vectorized crossing-parity test; boundary points count as inside
    (matching JTS `intersects` semantics closely enough for index tests —
    exact boundary behavior differs at shared-edge degeneracies).
    """
    px = np.asarray(px, dtype=np.float64)
    py = np.asarray(py, dtype=np.float64)
    inside = np.zeros(px.shape, dtype=bool)
    on_edge = np.zeros(px.shape, dtype=bool)
    for ring in polygon_rings(literal):
        x1, y1 = ring[:-1, 0], ring[:-1, 1]
        x2, y2 = ring[1:, 0], ring[1:, 1]
        # crossing parity (half-open rule), accumulated over all rings so
        # holes toggle points back out
        pyv = py[..., None]
        pxv = px[..., None]
        cond = (y1 > pyv) != (y2 > pyv)
        with np.errstate(divide="ignore", invalid="ignore"):
            xint = (x2 - x1) * (pyv - y1) / (y2 - y1) + x1
        crossings = cond & (pxv < xint)
        inside ^= (np.count_nonzero(crossings, axis=-1) % 2).astype(bool)
        # boundary test: point on segment
        on_edge |= _points_on_segments(px, py, np.concatenate(
            [ring[:-1], ring[1:]], axis=1))
    return inside | on_edge


def _points_on_segments(px, py, segs, eps: float = 1e-12) -> np.ndarray:
    """Whether each point lies on any segment (collinear + within extent)."""
    if len(segs) == 0:
        return np.zeros(np.shape(px), dtype=bool)
    x1, y1, x2, y2 = segs[:, 0], segs[:, 1], segs[:, 2], segs[:, 3]
    pxv, pyv = np.asarray(px)[..., None], np.asarray(py)[..., None]
    cross = (x2 - x1) * (pyv - y1) - (y2 - y1) * (pxv - x1)
    scale = np.maximum(np.abs(x2 - x1), np.abs(y2 - y1)) + eps
    collinear = np.abs(cross) <= eps * scale * np.maximum(1.0, np.maximum(np.abs(pxv), np.abs(pyv)))
    within = (
        (np.minimum(x1, x2) - eps <= pxv) & (pxv <= np.maximum(x1, x2) + eps)
        & (np.minimum(y1, y2) - eps <= pyv) & (pyv <= np.maximum(y1, y2) + eps)
    )
    return np.any(collinear & within, axis=-1)


def segments_cross(a: np.ndarray, b: np.ndarray) -> bool:
    """Whether any segment in a (n,4) crosses any in b (m,4). Proper and
    improper (touching) intersections both count."""
    if len(a) == 0 or len(b) == 0:
        return False
    ax1, ay1, ax2, ay2 = (a[:, i][:, None] for i in range(4))
    bx1, by1, bx2, by2 = (b[:, i][None, :] for i in range(4))

    def orient(ox, oy, px_, py_, qx, qy):
        return (px_ - ox) * (qy - oy) - (py_ - oy) * (qx - ox)

    d1 = orient(ax1, ay1, ax2, ay2, bx1, by1)
    d2 = orient(ax1, ay1, ax2, ay2, bx2, by2)
    d3 = orient(bx1, by1, bx2, by2, ax1, ay1)
    d4 = orient(bx1, by1, bx2, by2, ax2, ay2)
    proper = ((d1 * d2) < 0) & ((d3 * d4) < 0)
    if np.any(proper):
        return True

    def on(ox, oy, qx, qy, px_, py_, d):
        return (d == 0) & (np.minimum(ox, qx) <= px_) & (px_ <= np.maximum(ox, qx)) \
            & (np.minimum(oy, qy) <= py_) & (py_ <= np.maximum(oy, qy))

    touch = (
        on(ax1, ay1, ax2, ay2, bx1, by1, d1) | on(ax1, ay1, ax2, ay2, bx2, by2, d2)
        | on(bx1, by1, bx2, by2, ax1, ay1, d3) | on(bx1, by1, bx2, by2, ax2, ay2, d4)
    )
    return bool(np.any(touch))


def feature_segments(arr: "geo.GeometryArray", i: int) -> np.ndarray:
    """Boundary segments of feature i as (S, 4)."""
    return literal_segments(arr.shape(i))


def geometry_intersects(arr: "geo.GeometryArray", i: int, literal: tuple) -> bool:
    """Exact-ish intersects between feature i and a literal geometry.

    Covers: any feature vertex inside literal (polygonal), any literal vertex
    inside feature (polygonal feature), or boundary segments crossing. This is
    complete for all non-degenerate polygon/line/point combinations.
    """
    code = int(arr.type_codes[i])
    fcoords = arr.feature_coords(i)
    lcode = literal[0]

    if lcode in (geo.POLYGON, geo.MULTIPOLYGON):
        if np.any(points_in_polygon(fcoords[:, 0], fcoords[:, 1], literal)):
            return True
    if code in (geo.POLYGON, geo.MULTIPOLYGON):
        fshape = arr.shape(i)
        lc = literal_coords(literal)
        if np.any(points_in_polygon(lc[:, 0], lc[:, 1], fshape)):
            return True
    if lcode in (geo.POINT, geo.MULTIPOINT):
        lc = literal_coords(literal)
        if code in (geo.POINT, geo.MULTIPOINT):
            return bool(np.any((fcoords[:, None, 0] == lc[None, :, 0])
                               & (fcoords[:, None, 1] == lc[None, :, 1])))
        if code in (geo.LINESTRING, geo.MULTILINESTRING):
            return bool(np.any(_points_on_segments(lc[:, 0], lc[:, 1], feature_segments(arr, i))))
    if code in (geo.POINT, geo.MULTIPOINT) and lcode in (geo.LINESTRING, geo.MULTILINESTRING):
        return bool(np.any(_points_on_segments(fcoords[:, 0], fcoords[:, 1], literal_segments(literal))))
    return segments_cross(feature_segments(arr, i), literal_segments(literal))


def geometry_within(arr: "geo.GeometryArray", i: int, literal: tuple) -> bool:
    """Feature i entirely within a polygonal literal: all vertices inside and
    no boundary crossing out (approximate at shared boundaries)."""
    fcoords = arr.feature_coords(i)
    if not np.all(points_in_polygon(fcoords[:, 0], fcoords[:, 1], literal)):
        return False
    fsegs = feature_segments(arr, i)
    if len(fsegs) == 0:
        return True
    # vertices all inside: only a boundary crossing can place part outside
    return not _segments_properly_cross(fsegs, literal_segments(literal))


def _segments_properly_cross(a: np.ndarray, b: np.ndarray) -> bool:
    if len(a) == 0 or len(b) == 0:
        return False
    ax1, ay1, ax2, ay2 = (a[:, i][:, None] for i in range(4))
    bx1, by1, bx2, by2 = (b[:, i][None, :] for i in range(4))

    def orient(ox, oy, px_, py_, qx, qy):
        return (px_ - ox) * (qy - oy) - (py_ - oy) * (qx - ox)

    d1 = orient(ax1, ay1, ax2, ay2, bx1, by1)
    d2 = orient(ax1, ay1, ax2, ay2, bx2, by2)
    d3 = orient(bx1, by1, bx2, by2, ax1, ay1)
    d4 = orient(bx1, by1, bx2, by2, ax2, ay2)
    return bool(np.any(((d1 * d2) < 0) & ((d3 * d4) < 0)))


def point_segment_distance(px, py, segs: np.ndarray) -> np.ndarray:
    """Min distance from each point to any segment; (N,) array."""
    pxv = np.asarray(px, dtype=np.float64)[..., None]
    pyv = np.asarray(py, dtype=np.float64)[..., None]
    if len(segs) == 0:
        return np.full(np.shape(px), np.inf)
    x1, y1, x2, y2 = segs[:, 0], segs[:, 1], segs[:, 2], segs[:, 3]
    dx, dy = x2 - x1, y2 - y1
    ll = dx * dx + dy * dy
    with np.errstate(divide="ignore", invalid="ignore"):
        t = np.clip(((pxv - x1) * dx + (pyv - y1) * dy) / np.where(ll == 0, 1, ll), 0, 1)
    cx, cy = x1 + t * dx, y1 + t * dy
    return np.sqrt(np.min((pxv - cx) ** 2 + (pyv - cy) ** 2, axis=-1))


def geometry_distance(arr: "geo.GeometryArray", i: int, literal: tuple) -> float:
    """Approximate min distance between feature i and a literal (0 when they
    intersect; otherwise min vertex-to-boundary distance both ways)."""
    if geometry_intersects(arr, i, literal):
        return 0.0
    fcoords = arr.feature_coords(i)
    lsegs = literal_segments(literal)
    d = np.inf
    if len(lsegs):
        d = min(d, float(np.min(point_segment_distance(fcoords[:, 0], fcoords[:, 1], lsegs))))
    lc = literal_coords(literal)
    fsegs = feature_segments(arr, i)
    if len(fsegs):
        d = min(d, float(np.min(point_segment_distance(lc[:, 0], lc[:, 1], fsegs))))
    elif not len(lsegs):
        d = min(d, float(np.min(np.hypot(fcoords[:, None, 0] - lc[None, :, 0],
                                         fcoords[:, None, 1] - lc[None, :, 1]))))
    return d
