"""Extent × extent spatial join: grid partition → bbox pair generation →
device band refine → exact host refine of the uncertain sliver.

≙ the reference's Spark join machinery: `RelationUtils` spatial partitioning
(grid / weighted, /root/reference/geomesa-spark/geomesa-spark-sql/src/main/
scala/org/locationtech/geomesa/spark/RelationUtils.scala:85-160) feeding the
per-partition sweepline overlap join (GeoMesaJoinRelation.scala:41-56, JTS
SweepLineIndex + predicate evaluate). The TPU-native shape:

  - both sides' envelopes land on a density-sized grid; each geometry fans
    out to every cell its bbox overlaps (duplicate-and-own: a candidate pair
    is emitted only by the cell that contains the max of the two bbox min
    corners, the standard dedup that avoids a global unique pass)
  - candidate pairs stream out in bounded chunks (never a monolithic
    materialization — an overlap-heavy workload degrades to more chunks,
    not an error), filtered by envelope overlap — the moral equivalent of
    the sweepline, O(pairs) after gridding
  - surviving pairs refine on the DEVICE with the certified f32 band kernel
    (parallel/pair_kernel — the executor-side predicate evaluate of
    GeoMesaJoinRelation run on a chip), leaving only the uncertain sliver
    for the host's exact f64 geometry soups (filter/geom_batch), grouped by
    right-hand geometry so each group is one batched evaluation

Partitioned variant: row-band partitioning of the grid, each band an
independent join — the unit the dist layer shards over a device mesh
(pair_kernel.mesh_join_pairs is the whole-mesh form: pairs sharded,
geometry tables broadcast, psum'd hit counts)."""

from __future__ import annotations

from typing import Iterator, List, Optional, Tuple

import numpy as np

from geomesa_tpu import config
from geomesa_tpu import trace as _trace
from geomesa_tpu.features import geometry as geo
from geomesa_tpu.filter import geom_batch

# memory bound per candidate-pair chunk (NOT a failure cap: bigger joins
# stream through more chunks)
MAX_CANDIDATE_PAIRS = 50_000_000


def _cell_ranges(bb: np.ndarray, origin, csize, gx, gy):
    """Per-geometry inclusive grid-cell ranges covered by each bbox."""
    ix0 = np.clip(((bb[:, 0] - origin[0]) / csize[0]).astype(np.int64), 0, gx - 1)
    iy0 = np.clip(((bb[:, 1] - origin[1]) / csize[1]).astype(np.int64), 0, gy - 1)
    ix1 = np.clip(((bb[:, 2] - origin[0]) / csize[0]).astype(np.int64), 0, gx - 1)
    iy1 = np.clip(((bb[:, 3] - origin[1]) / csize[1]).astype(np.int64), 0, gy - 1)
    return ix0, iy0, ix1, iy1


def _fanout(ix0, iy0, ix1, iy1, gx):
    """(geom id, cell id) pairs for every covered cell (ragged iota)."""
    nx = ix1 - ix0 + 1
    ny = iy1 - iy0 + 1
    counts = nx * ny
    total = int(counts.sum())
    gid = np.repeat(np.arange(len(counts)), counts)
    local = np.arange(total) - np.repeat(np.cumsum(counts) - counts, counts)
    lx = local % np.repeat(nx, counts)
    ly = local // np.repeat(nx, counts)
    cell = (np.repeat(iy0, counts) + ly) * gx + (np.repeat(ix0, counts) + lx)
    return gid, cell


def candidate_pair_chunks(lbb: np.ndarray, rbb: np.ndarray,
                          grid: Optional[Tuple[int, int]] = None,
                          chunk_pairs: int = MAX_CANDIDATE_PAIRS
                          ) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
    """Stream (li, rj) candidate-pair chunks whose envelopes overlap,
    deduplicated via cell ownership. Each yielded chunk materializes at most
    ~``chunk_pairs`` raw pairs, so overlap-heavy workloads degrade to more
    chunks instead of raising (the reference never throws on join size; it
    partitions harder — RelationUtils weighted partitioning)."""
    if len(lbb) == 0 or len(rbb) == 0:
        return
    xmin = min(lbb[:, 0].min(), rbb[:, 0].min())
    ymin = min(lbb[:, 1].min(), rbb[:, 1].min())
    xmax = max(lbb[:, 2].max(), rbb[:, 2].max())
    ymax = max(lbb[:, 3].max(), rbb[:, 3].max())
    if grid is None:
        g = int(np.clip(np.sqrt((len(lbb) + len(rbb)) / 4.0), 1, 1024))
        grid = (g, g)
    gx, gy = grid
    csize = (max((xmax - xmin) / gx, 1e-9), max((ymax - ymin) / gy, 1e-9))
    origin = (xmin, ymin)

    l0x, l0y, l1x, l1y = _cell_ranges(lbb, origin, csize, gx, gy)
    r0x, r0y, r1x, r1y = _cell_ranges(rbb, origin, csize, gx, gy)
    lg, lc = _fanout(l0x, l0y, l1x, l1y, gx)
    rg, rc = _fanout(r0x, r0y, r1x, r1y, gx)

    # sort right entries by cell; for each left entry expand the right run
    # of its cell (ragged cross product per cell)
    order = np.argsort(rc, kind="stable")
    rc_s, rg_s = rc[order], rg[order]
    starts = np.searchsorted(rc_s, lc, side="left")
    stops = np.searchsorted(rc_s, lc, side="right")
    counts = stops - starts
    cum = np.cumsum(counts)
    total = int(cum[-1]) if len(cum) else 0
    if total == 0:
        return
    # split left-fanout entries into runs of <= chunk_pairs raw pairs
    cuts = [0]
    while cuts[-1] < len(counts):
        base = int(cum[cuts[-1] - 1]) if cuts[-1] else 0
        nxt = int(np.searchsorted(cum, base + chunk_pairs, side="right"))
        nxt = max(nxt, cuts[-1] + 1)  # always advance (one entry may exceed)
        cuts.append(min(nxt, len(counts)))

    for a, b in zip(cuts[:-1], cuts[1:]):
        cnt = counts[a:b]
        n = int(cnt.sum())
        if n == 0:
            continue
        li = np.repeat(lg[a:b], cnt)
        pos = np.arange(n) - np.repeat(np.cumsum(cnt) - cnt, cnt)
        rj = rg_s[np.repeat(starts[a:b], cnt) + pos]
        cell = np.repeat(lc[a:b], cnt)

        # envelope overlap + ownership dedup (the cell holding the pair's
        # max-of-mins corner owns it)
        lb = lbb[li]
        rb = rbb[rj]
        overlap = ((lb[:, 0] <= rb[:, 2]) & (lb[:, 2] >= rb[:, 0])
                   & (lb[:, 1] <= rb[:, 3]) & (lb[:, 3] >= rb[:, 1]))
        ox = np.maximum(lb[:, 0], rb[:, 0])
        oy = np.maximum(lb[:, 1], rb[:, 1])
        own_cell = (np.clip(((oy - origin[1]) / csize[1]).astype(np.int64),
                            0, gy - 1) * gx
                    + np.clip(((ox - origin[0]) / csize[0]).astype(np.int64),
                              0, gx - 1))
        keep = overlap & (own_cell == cell)
        if keep.any():
            yield li[keep], rj[keep]


def candidate_pairs(lbb: np.ndarray, rbb: np.ndarray,
                    grid: Optional[Tuple[int, int]] = None):
    """(li, rj) candidate pairs whose envelopes overlap (all chunks
    concatenated — the streaming form is ``candidate_pair_chunks``)."""
    out = list(candidate_pair_chunks(lbb, rbb, grid))
    if not out:
        return (np.empty(0, np.int64),) * 2
    return (np.concatenate([c[0] for c in out]),
            np.concatenate([c[1] for c in out]))


def _host_refine_mask(left: geo.GeometryArray, right: geo.GeometryArray,
                      li: np.ndarray, rj: np.ndarray, fn) -> np.ndarray:
    """Exact f64 predicate per pair, batched per distinct right geometry
    (each group is one geom_batch soup evaluation). Returns bool (P,)."""
    mask = np.zeros(len(li), dtype=bool)
    if len(li) == 0:
        return mask
    order = np.argsort(rj, kind="stable")
    rj_s = rj[order]
    bounds = np.flatnonzero(np.diff(rj_s)) + 1
    for seg_pos, j in zip(np.split(order, bounds),
                          rj_s[np.concatenate([[0], bounds])]):
        mask[seg_pos] = fn(left, li[seg_pos], right.shape(int(j)))
    return mask


def _refine_chunk(left: geo.GeometryArray, right: geo.GeometryArray,
                  li: np.ndarray, rj: np.ndarray, predicate: str,
                  device: str) -> np.ndarray:
    """Exact hit mask for one candidate chunk: device band kernel first
    (when it applies), host f64 for the uncertain sliver / fallback."""
    fn = geom_batch.batch_intersects if predicate == "intersects" \
        else geom_batch.batch_within
    use_device = (predicate == "intersects" and device != "never"
                  and (device == "always"
                       or len(li) >= config.JOIN_DEVICE_MIN_PAIRS.get()))
    if use_device:
        from geomesa_tpu.parallel.pair_kernel import device_refine
        with _trace.span("device_scan", kind="device_scan", pairs=len(li)):
            out = device_refine(left, right, li, rj)
        if out is not None:
            hit, unc = out
            if unc.any():
                u = np.flatnonzero(unc)
                hit = hit.copy()
                with _trace.span("refine", kind="refine", pairs=len(u)):
                    hit[u] = _host_refine_mask(left, right, li[u], rj[u], fn)
            return hit
    with _trace.span("refine", kind="refine", pairs=len(li)):
        return _host_refine_mask(left, right, li, rj, fn)


def extent_join(left: geo.GeometryArray, right: geo.GeometryArray,
                predicate: str = "intersects",
                grid: Optional[Tuple[int, int]] = None,
                device: str = "auto"):
    """Exact extent×extent join → (left ids, right ids) of matching pairs.

    Candidate pairs stream from the grid partitioner in bounded chunks;
    each chunk refines on the device (certified f32 bands, INTERSECTS) with
    host f64 only for the uncertain sliver — or fully on host for small
    chunks / WITHIN / unsupported shapes. ``device``: "auto" (size
    threshold, config JOIN_DEVICE_MIN_PAIRS), "always", "never".
    """
    if predicate not in ("intersects", "within"):
        raise ValueError(f"Unsupported join predicate {predicate!r}")
    with _trace.trace("extent_join", predicate=predicate,
                      left=len(left), right=len(right)):
        out_l: List[np.ndarray] = []
        out_r: List[np.ndarray] = []
        it = candidate_pair_chunks(left.bboxes(), right.bboxes(), grid)
        while True:
            # pull each candidate chunk under range_decompose — the grid
            # partitioner's work happens lazily inside the generator
            with _trace.span("range_decompose", kind="range_decompose"):
                chunk = next(it, None)
            if chunk is None:
                break
            li, rj = chunk
            hit = _refine_chunk(left, right, li, rj, predicate, device)
            out_l.append(li[hit])
            out_r.append(rj[hit])
        if not out_l:
            return np.empty(0, np.int64), np.empty(0, np.int64)
        with _trace.span("aggregate", kind="aggregate"):
            la = np.concatenate(out_l)
            ra = np.concatenate(out_r)
            order = np.lexsort((ra, la))
            return la[order], ra[order]


def extent_join_partitioned(left: geo.GeometryArray,
                            right: geo.GeometryArray,
                            n_partitions: int = 8,
                            predicate: str = "intersects",
                            device: str = "auto"):
    """Band-partitioned join: the grid's y-extent splits into bands, each an
    independent join over the geometries overlapping it (geometries fan out
    to every band they touch; pair ownership dedups at the band of the
    max-of-mins corner). This is the shuffle unit for a device mesh — each
    band's refine is independent work (≙ one Spark partition)."""
    lbb, rbb = left.bboxes(), right.bboxes()
    if len(lbb) == 0 or len(rbb) == 0:
        return np.empty(0, np.int64), np.empty(0, np.int64)
    ymin = min(lbb[:, 1].min(), rbb[:, 1].min())
    ymax = max(lbb[:, 3].max(), rbb[:, 3].max())
    h = max((ymax - ymin) / n_partitions, 1e-9)
    out_l, out_r = [], []
    for b in range(n_partitions):
        y0 = ymin + b * h
        y1 = ymin + (b + 1) * h
        lsel = np.flatnonzero((lbb[:, 3] >= y0) & (lbb[:, 1] <= y1))
        rsel = np.flatnonzero((rbb[:, 3] >= y0) & (rbb[:, 1] <= y1))
        if len(lsel) == 0 or len(rsel) == 0:
            continue
        la, ra = extent_join(left.take(lsel), right.take(rsel), predicate,
                             device=device)
        if len(la) == 0:
            continue
        gl, gr = lsel[la], rsel[ra]
        # band ownership: the pair belongs to the band of its overlap's ymin
        oy = np.maximum(lbb[gl, 1], rbb[gr, 1])
        own = np.clip(((oy - ymin) / h).astype(np.int64), 0, n_partitions - 1)
        keep = own == b
        out_l.append(gl[keep])
        out_r.append(gr[keep])
    if not out_l:
        return np.empty(0, np.int64), np.empty(0, np.int64)
    la = np.concatenate(out_l)
    ra = np.concatenate(out_r)
    order = np.lexsort((ra, la))
    return la[order], ra[order]
