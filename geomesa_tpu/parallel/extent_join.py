"""Extent × extent spatial join: grid partition → bbox pair generation →
exact geometry refine.

≙ the reference's Spark join machinery: `RelationUtils` spatial partitioning
(grid / weighted, /root/reference/geomesa-spark/geomesa-spark-sql/src/main/
scala/org/locationtech/geomesa/spark/RelationUtils.scala:85-160) feeding the
per-partition sweepline overlap join (GeoMesaJoinRelation.scala:41-56, JTS
SweepLineIndex + predicate evaluate). The TPU-native shape:

  - both sides' envelopes land on a density-sized grid; each geometry fans
    out to every cell its bbox overlaps (duplicate-and-own: a candidate pair
    is emitted only by the cell that contains the max of the two bbox min
    corners, the standard dedup that avoids a global unique pass)
  - candidate pairs filter by envelope overlap, all vectorized numpy — the
    moral equivalent of the sweepline, O(pairs) after gridding
  - surviving pairs refine with the exact vectorized geometry predicates
    (filter/geom_batch), grouped by right-hand geometry so each group is one
    batched soup evaluation

Partitioned variant: row-band partitioning of the grid, each band an
independent join — the unit the dist layer shards over a device mesh (host
shuffle ≙ the reference's Spark shuffle; the refine arithmetic is the part a
chip would accelerate)."""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from geomesa_tpu.features import geometry as geo
from geomesa_tpu.filter import geom_batch

MAX_CANDIDATE_PAIRS = 50_000_000


def _cell_ranges(bb: np.ndarray, origin, csize, gx, gy):
    """Per-geometry inclusive grid-cell ranges covered by each bbox."""
    ix0 = np.clip(((bb[:, 0] - origin[0]) / csize[0]).astype(np.int64), 0, gx - 1)
    iy0 = np.clip(((bb[:, 1] - origin[1]) / csize[1]).astype(np.int64), 0, gy - 1)
    ix1 = np.clip(((bb[:, 2] - origin[0]) / csize[0]).astype(np.int64), 0, gx - 1)
    iy1 = np.clip(((bb[:, 3] - origin[1]) / csize[1]).astype(np.int64), 0, gy - 1)
    return ix0, iy0, ix1, iy1


def _fanout(ix0, iy0, ix1, iy1, gx):
    """(geom id, cell id) pairs for every covered cell (ragged iota)."""
    nx = ix1 - ix0 + 1
    ny = iy1 - iy0 + 1
    counts = nx * ny
    total = int(counts.sum())
    gid = np.repeat(np.arange(len(counts)), counts)
    local = np.arange(total) - np.repeat(np.cumsum(counts) - counts, counts)
    lx = local % np.repeat(nx, counts)
    ly = local // np.repeat(nx, counts)
    cell = (np.repeat(iy0, counts) + ly) * gx + (np.repeat(ix0, counts) + lx)
    return gid, cell


def candidate_pairs(lbb: np.ndarray, rbb: np.ndarray,
                    grid: Optional[Tuple[int, int]] = None):
    """(li, rj) candidate pairs whose envelopes overlap, deduplicated via
    cell ownership. Pure vectorized host planning (≙ partition + sweepline)."""
    if len(lbb) == 0 or len(rbb) == 0:
        return (np.empty(0, np.int64),) * 2
    xmin = min(lbb[:, 0].min(), rbb[:, 0].min())
    ymin = min(lbb[:, 1].min(), rbb[:, 1].min())
    xmax = max(lbb[:, 2].max(), rbb[:, 2].max())
    ymax = max(lbb[:, 3].max(), rbb[:, 3].max())
    if grid is None:
        g = int(np.clip(np.sqrt((len(lbb) + len(rbb)) / 4.0), 1, 1024))
        grid = (g, g)
    gx, gy = grid
    csize = (max((xmax - xmin) / gx, 1e-9), max((ymax - ymin) / gy, 1e-9))
    origin = (xmin, ymin)

    l0x, l0y, l1x, l1y = _cell_ranges(lbb, origin, csize, gx, gy)
    r0x, r0y, r1x, r1y = _cell_ranges(rbb, origin, csize, gx, gy)
    lg, lc = _fanout(l0x, l0y, l1x, l1y, gx)
    rg, rc = _fanout(r0x, r0y, r1x, r1y, gx)

    # sort right entries by cell; for each left entry expand the right run
    # of its cell (ragged cross product per cell)
    order = np.argsort(rc, kind="stable")
    rc_s, rg_s = rc[order], rg[order]
    starts = np.searchsorted(rc_s, lc, side="left")
    stops = np.searchsorted(rc_s, lc, side="right")
    counts = stops - starts
    total = int(counts.sum())
    if total > MAX_CANDIDATE_PAIRS:
        raise ValueError(
            f"extent join candidate blow-up: {total} pairs (cap "
            f"{MAX_CANDIDATE_PAIRS}); refine the grid or pre-filter")
    li = np.repeat(lg, counts)
    pos = np.arange(total) - np.repeat(np.cumsum(counts) - counts, counts)
    rj = rg_s[np.repeat(starts, counts) + pos]
    cell = np.repeat(lc, counts)

    # envelope overlap + ownership dedup (the cell holding the pair's
    # max-of-mins corner owns it)
    lb = lbb[li]
    rb = rbb[rj]
    overlap = ((lb[:, 0] <= rb[:, 2]) & (lb[:, 2] >= rb[:, 0])
               & (lb[:, 1] <= rb[:, 3]) & (lb[:, 3] >= rb[:, 1]))
    ox = np.maximum(lb[:, 0], rb[:, 0])
    oy = np.maximum(lb[:, 1], rb[:, 1])
    own_cell = (np.clip(((oy - origin[1]) / csize[1]).astype(np.int64), 0, gy - 1) * gx
                + np.clip(((ox - origin[0]) / csize[0]).astype(np.int64), 0, gx - 1))
    keep = overlap & (own_cell == cell)
    return li[keep], rj[keep]


def extent_join(left: geo.GeometryArray, right: geo.GeometryArray,
                predicate: str = "intersects",
                grid: Optional[Tuple[int, int]] = None):
    """Exact extent×extent join → (left ids, right ids) of matching pairs.

    Candidate pairs come from the grid partitioner; the exact predicate
    evaluates with the vectorized geometry soups, batched per distinct
    right-hand geometry (each group is one geom_batch evaluation)."""
    if predicate not in ("intersects", "within"):
        raise ValueError(f"Unsupported join predicate {predicate!r}")
    li, rj = candidate_pairs(left.bboxes(), right.bboxes(), grid)
    if len(li) == 0:
        return li, rj
    fn = geom_batch.batch_intersects if predicate == "intersects" \
        else geom_batch.batch_within
    out_l: List[np.ndarray] = []
    out_r: List[np.ndarray] = []
    order = np.argsort(rj, kind="stable")
    li, rj = li[order], rj[order]
    bounds = np.flatnonzero(np.diff(rj)) + 1
    for seg_l, j in zip(np.split(li, bounds),
                        rj[np.concatenate([[0], bounds])] if len(li) else []):
        mask = fn(left, seg_l, right.shape(int(j)))
        out_l.append(seg_l[mask])
        out_r.append(np.full(int(mask.sum()), j, dtype=np.int64))
    if not out_l:
        return np.empty(0, np.int64), np.empty(0, np.int64)
    la = np.concatenate(out_l)
    ra = np.concatenate(out_r)
    order = np.lexsort((ra, la))
    return la[order], ra[order]


def extent_join_partitioned(left: geo.GeometryArray,
                            right: geo.GeometryArray,
                            n_partitions: int = 8,
                            predicate: str = "intersects"):
    """Band-partitioned join: the grid's y-extent splits into bands, each an
    independent join over the geometries overlapping it (geometries fan out
    to every band they touch; pair ownership dedups at the band of the
    max-of-mins corner). This is the shuffle unit for a device mesh — each
    band's refine is independent work (≙ one Spark partition)."""
    lbb, rbb = left.bboxes(), right.bboxes()
    if len(lbb) == 0 or len(rbb) == 0:
        return np.empty(0, np.int64), np.empty(0, np.int64)
    ymin = min(lbb[:, 1].min(), rbb[:, 1].min())
    ymax = max(lbb[:, 3].max(), rbb[:, 3].max())
    h = max((ymax - ymin) / n_partitions, 1e-9)
    out_l, out_r = [], []
    for b in range(n_partitions):
        y0 = ymin + b * h
        y1 = ymin + (b + 1) * h
        lsel = np.flatnonzero((lbb[:, 3] >= y0) & (lbb[:, 1] <= y1))
        rsel = np.flatnonzero((rbb[:, 3] >= y0) & (rbb[:, 1] <= y1))
        if len(lsel) == 0 or len(rsel) == 0:
            continue
        la, ra = extent_join(left.take(lsel), right.take(rsel), predicate)
        if len(la) == 0:
            continue
        gl, gr = lsel[la], rsel[ra]
        # band ownership: the pair belongs to the band of its overlap's ymin
        oy = np.maximum(lbb[gl, 1], rbb[gr, 1])
        own = np.clip(((oy - ymin) / h).astype(np.int64), 0, n_partitions - 1)
        keep = own == b
        out_l.append(gl[keep])
        out_r.append(gr[keep])
    if not out_l:
        return np.empty(0, np.int64), np.empty(0, np.int64)
    la = np.concatenate(out_l)
    ra = np.concatenate(out_r)
    order = np.lexsort((ra, la))
    return la[order], ra[order]
