"""Device mesh + row-sharded tables.

Sharding model: one logical axis ``rows``. The index-sorted table (epoch-major
for temporal indexes) is padded to a multiple of the device count and laid out
with ``NamedSharding(P("rows"))``, so each device owns a contiguous key-range
slice — the reference's tablet/region split discipline
(DefaultSplitter.scala:34). The reference derives split points from stat
histograms because its splits are KEY-valued and the key distribution is
unknown; here splits are ROW-COUNT-valued over an already-sorted layout, so
equal row counts ARE the exact key-quantile splits the stats-driven splitter
approximates — perfect balance by construction. ``split_points`` surfaces
the resulting per-device key boundaries for ops parity.

Pad rows carry ``__valid__ = False`` and out-of-domain key values so no
predicate can match them; the mask kernels AND the valid plane when present.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def create_mesh(n_devices: Optional[int] = None, axis: str = "rows") -> Mesh:
    """The row mesh. Topology routes through the cluster runtime when a
    multi-process cluster is active (process-contiguous device order,
    hybrid ICI x DCN across slices — cluster/runtime.py); otherwise a
    flat mesh over the local devices.

    An impossible ``n_devices`` raises instead of silently truncating:
    computing on a partial device set while the caller believes it has
    the mesh it asked for is exactly the quiet-wrong-answer failure the
    cluster config is meant to rule out."""
    if n_devices is not None and n_devices < 1:
        raise ValueError(f"create_mesh: n_devices={n_devices} (want >= 1)")
    from geomesa_tpu.cluster.runtime import cluster_active, runtime
    if cluster_active():
        mesh = runtime().mesh(axis)
        if n_devices is not None and n_devices != mesh.devices.size:
            raise ValueError(
                f"create_mesh: n_devices={n_devices} conflicts with the "
                f"active cluster mesh ({mesh.devices.size} devices over "
                f"{runtime().num_processes} processes); topology is owned "
                "by GEOMESA_TPU_CLUSTER_* config")
        return mesh
    devs = jax.devices()
    if n_devices is not None:
        if n_devices > len(devs):
            raise ValueError(
                f"create_mesh: {n_devices} devices requested but only "
                f"{len(devs)} present — refusing to silently truncate")
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis,))


@dataclass
class ShardedTable:
    """Row-sharded device columns + replication helpers."""

    mesh: Mesh
    n: int               # true row count (pre-padding)
    n_padded: int
    columns: Dict[str, jnp.ndarray]
    # host refs to the unpadded coordinate columns, kept so k-limited
    # reductions (knn) can re-rank their f32 margin exactly on host
    host_xy: Optional[tuple] = None

    @classmethod
    def from_host_columns(cls, mesh: Mesh, host_cols: Dict[str, np.ndarray]) -> "ShardedTable":
        n_dev = mesh.devices.size
        n = len(next(iter(host_cols.values())))
        n_padded = ((n + n_dev - 1) // n_dev) * n_dev
        sharding = NamedSharding(mesh, P("rows"))
        cols: Dict[str, jnp.ndarray] = {}
        host_xy = None
        if "xf" in host_cols and "yf" in host_cols:
            host_xy = (np.asarray(host_cols["xf"]), np.asarray(host_cols["yf"]))
        for name, arr in host_cols.items():
            arr = np.asarray(arr)
            if n_padded != n:
                pad_val = _pad_value(name, arr.dtype)
                pad = np.full((n_padded - n,) + arr.shape[1:], pad_val, dtype=arr.dtype)
                arr = np.concatenate([arr, pad])
            cols[name] = jax.device_put(arr, sharding)
        valid = np.zeros(n_padded, dtype=bool)
        valid[:n] = True
        cols["__valid__"] = jax.device_put(valid, sharding)
        return cls(mesh, n, n_padded, cols, host_xy)

    @classmethod
    def from_process_local(cls, rt, local_cols: Dict[str, np.ndarray],
                           key_bounds=None, axis: str = "rows"):
        """The multi-process construction path: THIS process's contiguous
        key-range shard assembles into one global array with
        ``jax.make_array_from_process_local_data`` (cluster/table.py).
        Collective across the cluster; single-process it degrades to
        ``from_host_columns``."""
        from geomesa_tpu.cluster.table import ClusterShardedTable
        return ClusterShardedTable.from_local_columns(
            rt, local_cols, key_bounds=key_bounds, axis=axis)

    def replicated(self, arr: np.ndarray) -> jnp.ndarray:
        """Place query constants replicated on every device."""
        return jax.device_put(np.asarray(arr), NamedSharding(self.mesh, P()))


def shard_spans(n: int, n_devices: int):
    """Contiguous, maximally balanced [offset, offset+len) row spans for an
    n-row table over ``n_devices`` shards (first ``n % n_devices`` shards
    take the extra row). The build-sort sharding analogue of the row-quantile
    split above — used by parallel.dist.mesh_sort_perm to scatter unsorted
    key planes."""
    base, rem = divmod(n, n_devices)
    spans = []
    off = 0
    for i in range(n_devices):
        m = base + (1 if i < rem else 0)
        spans.append((off, m))
        off += m
    return spans


def split_points(sorted_keys: np.ndarray, n_devices: int) -> np.ndarray:
    """Per-device key boundaries of the row-quantile sharding (≙ the split
    points DefaultSplitter derives from stat histograms; here they are read
    off the sorted keys directly)."""
    n = len(sorted_keys)
    cuts = (np.arange(1, n_devices) * n) // n_devices
    return np.asarray(sorted_keys)[np.minimum(cuts, max(0, n - 1))]


def _pad_value(name: str, dtype) -> object:
    """Out-of-domain pad so padded rows fail every primary predicate."""
    if dtype == np.bool_:
        return False
    if np.issubdtype(dtype, np.integer):
        return -1 if name in ("xi", "yi", "bin", "off") else 0
    return np.nan if np.issubdtype(dtype, np.floating) else 0
