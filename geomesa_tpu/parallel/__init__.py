"""Distributed execution over a TPU device mesh (≙ SURVEY.md §2.12).

The reference parallelizes by fanning query ranges across storage servers and
merging per-server partials (BatchScanPlan, FeatureReducer). The TPU-native
equivalent: shard the index-sorted columnar table across devices on a ``rows``
mesh axis (epoch-major order → devices own contiguous epoch/z slices, the
moral of region splits), replicate query constants, and let XLA insert the
collectives (psum for counts/stats/density merges — the FeatureReducer step —
all_gather only for survivor-row hydration).

  - ``mesh``        — mesh construction + ShardedTable
  - ``dist``        — distributed count/density/stats query steps
  - ``join``        — broadcast-polygon spatial join with psum hit counts
  - ``extent_join`` — grid-partitioned extent×extent join + exact refine
"""

from geomesa_tpu.parallel.extent_join import extent_join, extent_join_partitioned
from geomesa_tpu.parallel.mesh import ShardedTable, create_mesh

__all__ = ["ShardedTable", "create_mesh", "extent_join",
           "extent_join_partitioned"]
