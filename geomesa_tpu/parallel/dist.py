"""Distributed query execution over a row-sharded table.

≙ the reference's scatter-gather scan fan-out (BatchScanPlan across tablet
servers + client FeatureReducer merge, SURVEY.md §3.3 steps 6-8) — except the
"servers" are mesh devices, partial results merge over ICI via the collectives
XLA inserts for the sharded-in/replicated-out computations, and there is no
client RPC at all:

  count    — sharded mask → global sum (psum)
  density  — sharded scatter-add partial grids → replicated (H, W) (psum)
  select   — per-device compaction; survivors gather to host (the only
             ragged/host-merged step, as in the reference's client merge)
  knn      — sharded distance + per-shard top-k; XLA's sharded top_k merges
             the per-device candidate sets into the global k over ICI (the
             distributed form of the device KNN kernel)

All entry points are jit-compiled once per (structure, shape) and reused.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from geomesa_tpu.aggregates.density import density_kernel
from geomesa_tpu.index.scan import ModuleKernelCache, PRIMARY_FNS, _time_mask
from geomesa_tpu.parallel.mesh import ShardedTable


def _build_mask(cols, primary_kind: str, boxes, windows, residual_fn, rparams):
    m = None
    if primary_kind != "none" and boxes is not None:
        m = PRIMARY_FNS[primary_kind](cols, boxes)
    if windows is not None:
        tm = _time_mask(cols, windows)
        m = tm if m is None else m & tm
    if residual_fn is not None:
        rm = residual_fn(cols, rparams)
        m = rm if m is None else m & rm
    if m is None:
        m = jnp.ones(next(iter(cols.values())).shape[0], dtype=bool)
    if "__valid__" in cols:
        m = m & cols["__valid__"]
    return m


class DistributedScan:
    """Distributed count/density/select over a ShardedTable."""

    def __init__(self, sharded: ShardedTable):
        self.sharded = sharded
        self._jitted: Dict[tuple, object] = {}

    def _fn(self, key, builder):
        if key not in self._jitted:
            self._jitted[key] = builder()
        return self._jitted[key]

    def _jit(self, fn, replicated_out: bool = False):
        """Compile one scan step. ``replicated_out`` marks the reductions
        whose result must be identical everywhere (count/density/knn) —
        the single-process hook point cluster.exec.ClusterScan overrides
        with ``out_shardings=NamedSharding(mesh, P())`` so XLA inserts
        the cross-process psum and EVERY process returns the exact
        global answer."""
        return jax.jit(fn)

    def _stage(self, plan):
        """(rkey, rfn, boxes, windows, rparams) — shared plan staging:
        residual unpack + replicated query constants (one home for the four
        scan entry points)."""
        res = plan.residual_device
        rkey = res[0] if res else "none"
        rfn = res[2] if res else None
        boxes = None if plan.boxes_loose is None \
            else self.sharded.replicated(plan.boxes_loose)
        windows = None if plan.windows is None \
            else self.sharded.replicated(plan.windows)
        rparams = [self.sharded.replicated(p) for p in res[1]] if res else []
        return rkey, rfn, boxes, windows, rparams

    def count(self, plan) -> int:
        rkey, rfn, boxes, windows, rparams = self._stage(plan)
        key = ("count", plan.primary_kind, plan.windows is not None, rkey)

        def build():
            def step(cols, boxes, windows, rparams):
                return jnp.sum(_build_mask(cols, plan.primary_kind, boxes,
                                           windows, rfn, rparams))
            return self._jit(step, replicated_out=True)

        fn = self._fn(key, build)
        return int(fn(self.sharded.columns, boxes, windows, rparams))

    def density(self, plan, bbox, width: int, height: int,
                weight_attr: Optional[str] = None) -> np.ndarray:
        rkey, rfn, boxes, windows, rparams = self._stage(plan)
        key = ("density", plan.primary_kind, plan.windows is not None, rkey,
               width, height, weight_attr)

        def build():
            def step(cols, boxes, windows, rparams, grid):
                m = _build_mask(cols, plan.primary_kind, boxes, windows, rfn, rparams)
                w = cols[weight_attr] if weight_attr else None
                return density_kernel(m, cols["xf"], cols["yf"], grid, width, height, w)
            return self._jit(step, replicated_out=True)

        fn = self._fn(key, build)
        grid = self.sharded.replicated(np.asarray(bbox, dtype=np.float32))
        return np.asarray(fn(self.sharded.columns, boxes, windows, rparams, grid))

    def knn(self, plan, x: float, y: float, k: int):
        """(global row ids, distances_m f32) of the k nearest masked rows
        across every shard: one jitted program computes sharded haversine
        distances and a top-k whose merge XLA lowers to per-shard top-k +
        an ICI combine (the FeatureReducer step as a collective).

        Requires a fully device-servable plan — a host residual cannot be
        applied after a k-limited reduction (unlike select, there is nothing
        left to refine), so such plans are rejected rather than silently
        answering the wrong question."""
        from geomesa_tpu.index.scan import _haversine_f32

        if plan.residual_host is not None or plan.candidate_slices is not None:
            raise ValueError(
                "distributed knn needs a device-exact plan (host residuals "
                "cannot refine a k-limited result)")
        rkey, rfn, boxes, windows, rparams = self._stage(plan)
        # ≥2k margin: f32 distance rounding can swap membership right at the
        # k-th boundary, so over-fetch and re-rank the margin in f64 on host
        # (same discipline as process/knn._exact_rerank)
        m_cap = min(max(32, 1 << (max(0, 2 * k - 1)).bit_length()),
                    self.sharded.n_padded)
        key = ("knn", plan.primary_kind, plan.windows is not None, rkey, m_cap)

        def build():
            def step(cols, boxes, windows, rparams, q):
                m = _build_mask(cols, plan.primary_kind, boxes, windows,
                                rfn, rparams)
                d = _haversine_f32(cols["xf"], cols["yf"], q[0], q[1])
                d = jnp.where(m, d, jnp.inf)
                vals, idxs = jax.lax.top_k(-d, m_cap)
                return -vals, idxs
            return self._jit(step, replicated_out=True)

        fn = self._fn(key, build)
        q = self.sharded.replicated(np.array([x, y], dtype=np.float32))
        dists, idxs = fn(self.sharded.columns, boxes, windows, rparams, q)
        dists = np.asarray(dists)
        idxs = np.asarray(idxs)
        valid = np.isfinite(dists)
        idxs, dists = idxs[valid], dists[valid]
        if self.sharded.host_xy is not None and len(idxs):
            from geomesa_tpu.process.geo import haversine_m
            gx, gy = self.sharded.host_xy
            d = haversine_m(gx[idxs].astype(np.float64),
                            gy[idxs].astype(np.float64), x, y)
            order = np.argsort(d, kind="stable")[:k]
            # rank in f64, deliver f32 (the documented contract either path)
            return idxs[order], d[order].astype(np.float32)
        return idxs[:k], dists[:k]

    def mask(self, plan) -> np.ndarray:
        """Full boolean mask gathered to host (hydration path)."""
        rkey, rfn, boxes, windows, rparams = self._stage(plan)
        key = ("mask", plan.primary_kind, plan.windows is not None, rkey)

        def build():
            def step(cols, boxes, windows, rparams):
                return _build_mask(cols, plan.primary_kind, boxes, windows, rfn, rparams)
            return self._jit(step)

        fn = self._fn(key, build)
        return np.asarray(fn(self.sharded.columns, boxes, windows, rparams))[: self.sharded.n]


# -- mesh-sharded index-key sort ---------------------------------------------
#
# ≙ the reference's distributed write path: each tablet server sorts its own
# key range and the split points define the ranges (SNIPPETS partitioner
# pattern). Here: per-shard lax.sort of the key planes (+ a row-id plane so
# ties break on original row order, exactly like the single-device program's
# iota tie-break), a sample-based splitter exchange on the host, per-shard
# lexicographic partition counts on device, then a per-partition merge sort
# on the partition's owner device. Partitioning is by KEY ONLY (rows with
# equal keys all land in one partition, where the row-id plane orders them),
# so the concatenated result is bitwise identical to a single stable sort.

_I32_MAX = np.iinfo(np.int32).max

_MESH_SORT_CACHE = ModuleKernelCache("build.mesh_sort")


def shard_devices():
    """Devices participating in the mesh-sharded sort
    (GEOMESA_TPU_SHARD_SORT_DEVICES caps the count; 0 = all local)."""
    from geomesa_tpu import config
    devs = jax.devices()
    cap = config.SHARD_SORT_DEVICES.get()
    if cap and cap > 0:
        devs = devs[:cap]
    return devs


def mesh_sort_enabled(n: int) -> bool:
    """True when the mesh-sharded sort should run for an n-row build."""
    from geomesa_tpu import config
    if not config.SHARD_SORT.get():
        return False
    if n < config.SHARD_SORT_MIN.get():
        return False
    return len(shard_devices()) >= 2


def _sort_jit(nargs: int, cap: int):
    """Full sort of ``nargs`` equal-length int32 planes, every plane a key
    (major → minor; the last plane is the row-id tie-break)."""
    def build():
        def fn(args):
            from jax import lax
            return lax.sort(tuple(args), num_keys=len(args))
        return jax.jit(fn)
    return _MESH_SORT_CACHE.get(("sort", nargs, cap), build)


def _count_lt_jit(nplanes: int, cap: int, nspl: int):
    """Per-splitter count of rows with key lexicographically < splitter.
    Pad rows (all planes int32-max) always compare ≥ any real splitter, so
    they never count."""
    def build():
        def fn(planes, spl):
            lt = planes[-1][:, None] < spl[-1][None, :]
            for p, s in zip(reversed(planes[:-1]), reversed(spl[:-1])):
                lt = (p[:, None] < s[None, :]) \
                    | ((p[:, None] == s[None, :]) & lt)
            return jnp.sum(lt, axis=0, dtype=jnp.int32)
        return jax.jit(fn)
    return _MESH_SORT_CACHE.get(("count_lt", nplanes, cap, nspl), build)


def _pad_sorted(args, cap: int):
    return [jnp.pad(a, (0, cap - a.shape[0]), constant_values=_I32_MAX)
            if a.shape[0] < cap else a for a in args]


def mesh_sort_perm(planes=None, shards=None, n: Optional[int] = None,
                   type_name: Optional[str] = None,
                   stages: Optional[dict] = None):
    """Stable sort permutation of int32 key planes, sharded across devices.

    Either ``planes`` (host int32 arrays, split contiguously here) or
    ``shards`` (per-device lists of ``(row_offset, [plane arrays])`` chunks,
    e.g. from the round-robin streaming upload) supplies the keys. Returns
    the int32 permutation on the default device — bitwise identical to
    ``np.lexsort(tuple(reversed(planes)))``.

    Scope: LOCAL devices. Across process boundaries the same splitter
    discipline continues host-side in cluster/build.py:cluster_partition
    (sample exchange -> global splitters -> row exchange), so a
    multi-process index build lands each process a contiguous sorted key
    range with no post-hoc global sort.
    """
    import time as _time

    from geomesa_tpu import config
    from geomesa_tpu.obs.profiling import PROGRESS as _progress

    devs = shard_devices()
    ndev = len(devs)
    if planes is not None:
        from geomesa_tpu.parallel.mesh import shard_spans
        n = len(planes[0])
        nplanes = len(planes)
        shards = [[(off, [jax.device_put(p[off:off + m], devs[i])
                          for p in planes])]
                  for i, (off, m) in enumerate(shard_spans(n, ndev))]
    else:
        nplanes = len(shards[0][0][1]) if any(shards) else 0
        for chunks in shards:
            if chunks:
                nplanes = len(chunks[0][1])
                break
    if stages is None:
        stages = {}
    stages["shards"] = ndev

    # phase 1: per-shard stable sort (planes + row-id plane)
    t0 = _time.perf_counter()
    shard_sorted = []   # per shard: list of sorted arrays (planes + rowid)
    shard_valid = []
    with _progress.phase("shard_sort", rows=n, type_name=type_name):
        for i in range(ndev):
            chunks = shards[i] if i < len(shards) else []
            parts = [[] for _ in range(nplanes + 1)]
            valid = 0
            for off, arrs in chunks:
                m = int(arrs[0].shape[0])
                valid += m
                for k in range(nplanes):
                    parts[k].append(arrs[k])
                parts[nplanes].append(jax.device_put(
                    np.arange(off, off + m, dtype=np.int32), devs[i]))
            if valid == 0:
                shard_sorted.append(None)
                shard_valid.append(0)
                continue
            args = [p[0] if len(p) == 1 else jnp.concatenate(p)
                    for p in parts]
            cap = 1 << max(0, (valid - 1)).bit_length()
            args = _pad_sorted(args, cap)
            out = _sort_jit(nplanes + 1, cap)(tuple(args))
            shard_sorted.append(list(out))
            shard_valid.append(valid)
        jax.block_until_ready([a for s in shard_sorted if s for a in s])
    stages["shard_sort_s"] = round(_time.perf_counter() - t0, 3)

    # phase 2: sample-based splitter exchange + partition bounds
    t0 = _time.perf_counter()
    with _progress.phase("splitter_exchange", rows=n, type_name=type_name):
        k_samples = max(2, config.SHARD_SORT_SAMPLES.get())
        sample_cols = [[] for _ in range(nplanes)]
        for i in range(ndev):
            if shard_valid[i] == 0:
                continue
            pos = np.unique(np.linspace(
                0, shard_valid[i] - 1,
                num=min(k_samples, shard_valid[i])).astype(np.int64))
            for k in range(nplanes):
                sample_cols[k].append(
                    np.asarray(shard_sorted[i][k][pos]))
        samples = [np.concatenate(c) for c in sample_cols]
        order = np.lexsort(tuple(reversed(samples)))
        total = len(order)
        spl_idx = [order[(total * j) // ndev] for j in range(1, ndev)]
        splitters = [np.asarray([samples[k][i] for i in spl_idx],
                                dtype=np.int32) for k in range(nplanes)]
        bounds = []   # per shard: partition boundaries [0, ..., valid]
        for i in range(ndev):
            if shard_valid[i] == 0:
                bounds.append([0] * (ndev + 1))
                continue
            cap = int(shard_sorted[i][0].shape[0])
            spl_dev = tuple(jax.device_put(s, devs[i]) for s in splitters)
            counts = np.asarray(_count_lt_jit(nplanes, cap, ndev - 1)(
                tuple(shard_sorted[i][:nplanes]), spl_dev))
            bounds.append([0] + [int(c) for c in counts] + [shard_valid[i]])
    stages["splitter_exchange_s"] = round(_time.perf_counter() - t0, 3)

    # phase 3: per-partition merge sort on the partition's owner device,
    # then concatenate the row-id planes in splitter order on device 0
    t0 = _time.perf_counter()
    with _progress.phase("merge", rows=n, type_name=type_name):
        perm_parts = []
        for j in range(ndev):
            pieces = [[] for _ in range(nplanes + 1)]
            m_j = 0
            for i in range(ndev):
                if shard_valid[i] == 0:
                    continue
                b0, b1 = bounds[i][j], bounds[i][j + 1]
                if b1 <= b0:
                    continue
                m_j += b1 - b0
                for k in range(nplanes + 1):
                    pieces[k].append(jax.device_put(
                        shard_sorted[i][k][b0:b1], devs[j]))
            if m_j == 0:
                continue
            args = [p[0] if len(p) == 1 else jnp.concatenate(p)
                    for p in pieces]
            cap = 1 << max(0, (m_j - 1)).bit_length()
            args = _pad_sorted(args, cap)
            out = _sort_jit(nplanes + 1, cap)(tuple(args))
            perm_parts.append(jax.device_put(out[-1][:m_j],
                                             jax.devices()[0]))
        perm = perm_parts[0] if len(perm_parts) == 1 \
            else jnp.concatenate(perm_parts)
        jax.block_until_ready(perm)
    stages["merge_s"] = round(_time.perf_counter() - t0, 3)
    return perm
