"""Distributed query execution over a row-sharded table.

≙ the reference's scatter-gather scan fan-out (BatchScanPlan across tablet
servers + client FeatureReducer merge, SURVEY.md §3.3 steps 6-8) — except the
"servers" are mesh devices, partial results merge over ICI via the collectives
XLA inserts for the sharded-in/replicated-out computations, and there is no
client RPC at all:

  count    — sharded mask → global sum (psum)
  density  — sharded scatter-add partial grids → replicated (H, W) (psum)
  select   — per-device compaction; survivors gather to host (the only
             ragged/host-merged step, as in the reference's client merge)

All entry points are jit-compiled once per (structure, shape) and reused.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from geomesa_tpu.aggregates.density import density_kernel
from geomesa_tpu.index.scan import PRIMARY_FNS, _time_mask
from geomesa_tpu.parallel.mesh import ShardedTable


def _build_mask(cols, primary_kind: str, boxes, windows, residual_fn, rparams):
    m = None
    if primary_kind != "none" and boxes is not None:
        m = PRIMARY_FNS[primary_kind](cols, boxes)
    if windows is not None:
        tm = _time_mask(cols, windows)
        m = tm if m is None else m & tm
    if residual_fn is not None:
        rm = residual_fn(cols, rparams)
        m = rm if m is None else m & rm
    if m is None:
        m = jnp.ones(next(iter(cols.values())).shape[0], dtype=bool)
    if "__valid__" in cols:
        m = m & cols["__valid__"]
    return m


class DistributedScan:
    """Distributed count/density/select over a ShardedTable."""

    def __init__(self, sharded: ShardedTable):
        self.sharded = sharded
        self._jitted: Dict[tuple, object] = {}

    def _fn(self, key, builder):
        if key not in self._jitted:
            self._jitted[key] = builder()
        return self._jitted[key]

    def count(self, plan) -> int:
        res = plan.residual_device
        rkey = res[0] if res else "none"
        rfn = res[2] if res else None
        key = ("count", plan.primary_kind, plan.windows is not None, rkey)

        def build():
            def step(cols, boxes, windows, rparams):
                return jnp.sum(_build_mask(cols, plan.primary_kind, boxes,
                                           windows, rfn, rparams))
            return jax.jit(step)

        fn = self._fn(key, build)
        boxes = None if plan.boxes_loose is None else self.sharded.replicated(plan.boxes_loose)
        windows = None if plan.windows is None else self.sharded.replicated(plan.windows)
        rparams = [self.sharded.replicated(p) for p in res[1]] if res else []
        return int(fn(self.sharded.columns, boxes, windows, rparams))

    def density(self, plan, bbox, width: int, height: int,
                weight_attr: Optional[str] = None) -> np.ndarray:
        res = plan.residual_device
        rkey = res[0] if res else "none"
        rfn = res[2] if res else None
        key = ("density", plan.primary_kind, plan.windows is not None, rkey,
               width, height, weight_attr)

        def build():
            def step(cols, boxes, windows, rparams, grid):
                m = _build_mask(cols, plan.primary_kind, boxes, windows, rfn, rparams)
                w = cols[weight_attr] if weight_attr else None
                return density_kernel(m, cols["xf"], cols["yf"], grid, width, height, w)
            return jax.jit(step)

        fn = self._fn(key, build)
        boxes = None if plan.boxes_loose is None else self.sharded.replicated(plan.boxes_loose)
        windows = None if plan.windows is None else self.sharded.replicated(plan.windows)
        rparams = [self.sharded.replicated(p) for p in res[1]] if res else []
        grid = self.sharded.replicated(np.asarray(bbox, dtype=np.float32))
        return np.asarray(fn(self.sharded.columns, boxes, windows, rparams, grid))

    def mask(self, plan) -> np.ndarray:
        """Full boolean mask gathered to host (hydration path)."""
        res = plan.residual_device
        rkey = res[0] if res else "none"
        rfn = res[2] if res else None
        key = ("mask", plan.primary_kind, plan.windows is not None, rkey)

        def build():
            def step(cols, boxes, windows, rparams):
                return _build_mask(cols, plan.primary_kind, boxes, windows, rfn, rparams)
            return jax.jit(step)

        fn = self._fn(key, build)
        boxes = None if plan.boxes_loose is None else self.sharded.replicated(plan.boxes_loose)
        windows = None if plan.windows is None else self.sharded.replicated(plan.windows)
        rparams = [self.sharded.replicated(p) for p in res[1]] if res else []
        return np.asarray(fn(self.sharded.columns, boxes, windows, rparams))[: self.sharded.n]
