"""Distributed query execution over a row-sharded table.

≙ the reference's scatter-gather scan fan-out (BatchScanPlan across tablet
servers + client FeatureReducer merge, SURVEY.md §3.3 steps 6-8) — except the
"servers" are mesh devices, partial results merge over ICI via the collectives
XLA inserts for the sharded-in/replicated-out computations, and there is no
client RPC at all:

  count    — sharded mask → global sum (psum)
  density  — sharded scatter-add partial grids → replicated (H, W) (psum)
  select   — per-device compaction; survivors gather to host (the only
             ragged/host-merged step, as in the reference's client merge)
  knn      — sharded distance + per-shard top-k; XLA's sharded top_k merges
             the per-device candidate sets into the global k over ICI (the
             distributed form of the device KNN kernel)

All entry points are jit-compiled once per (structure, shape) and reused.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from geomesa_tpu.aggregates.density import density_kernel
from geomesa_tpu.index.scan import PRIMARY_FNS, _time_mask
from geomesa_tpu.parallel.mesh import ShardedTable


def _build_mask(cols, primary_kind: str, boxes, windows, residual_fn, rparams):
    m = None
    if primary_kind != "none" and boxes is not None:
        m = PRIMARY_FNS[primary_kind](cols, boxes)
    if windows is not None:
        tm = _time_mask(cols, windows)
        m = tm if m is None else m & tm
    if residual_fn is not None:
        rm = residual_fn(cols, rparams)
        m = rm if m is None else m & rm
    if m is None:
        m = jnp.ones(next(iter(cols.values())).shape[0], dtype=bool)
    if "__valid__" in cols:
        m = m & cols["__valid__"]
    return m


class DistributedScan:
    """Distributed count/density/select over a ShardedTable."""

    def __init__(self, sharded: ShardedTable):
        self.sharded = sharded
        self._jitted: Dict[tuple, object] = {}

    def _fn(self, key, builder):
        if key not in self._jitted:
            self._jitted[key] = builder()
        return self._jitted[key]

    def _stage(self, plan):
        """(rkey, rfn, boxes, windows, rparams) — shared plan staging:
        residual unpack + replicated query constants (one home for the four
        scan entry points)."""
        res = plan.residual_device
        rkey = res[0] if res else "none"
        rfn = res[2] if res else None
        boxes = None if plan.boxes_loose is None \
            else self.sharded.replicated(plan.boxes_loose)
        windows = None if plan.windows is None \
            else self.sharded.replicated(plan.windows)
        rparams = [self.sharded.replicated(p) for p in res[1]] if res else []
        return rkey, rfn, boxes, windows, rparams

    def count(self, plan) -> int:
        rkey, rfn, boxes, windows, rparams = self._stage(plan)
        key = ("count", plan.primary_kind, plan.windows is not None, rkey)

        def build():
            def step(cols, boxes, windows, rparams):
                return jnp.sum(_build_mask(cols, plan.primary_kind, boxes,
                                           windows, rfn, rparams))
            return jax.jit(step)

        fn = self._fn(key, build)
        return int(fn(self.sharded.columns, boxes, windows, rparams))

    def density(self, plan, bbox, width: int, height: int,
                weight_attr: Optional[str] = None) -> np.ndarray:
        rkey, rfn, boxes, windows, rparams = self._stage(plan)
        key = ("density", plan.primary_kind, plan.windows is not None, rkey,
               width, height, weight_attr)

        def build():
            def step(cols, boxes, windows, rparams, grid):
                m = _build_mask(cols, plan.primary_kind, boxes, windows, rfn, rparams)
                w = cols[weight_attr] if weight_attr else None
                return density_kernel(m, cols["xf"], cols["yf"], grid, width, height, w)
            return jax.jit(step)

        fn = self._fn(key, build)
        grid = self.sharded.replicated(np.asarray(bbox, dtype=np.float32))
        return np.asarray(fn(self.sharded.columns, boxes, windows, rparams, grid))

    def knn(self, plan, x: float, y: float, k: int):
        """(global row ids, distances_m f32) of the k nearest masked rows
        across every shard: one jitted program computes sharded haversine
        distances and a top-k whose merge XLA lowers to per-shard top-k +
        an ICI combine (the FeatureReducer step as a collective).

        Requires a fully device-servable plan — a host residual cannot be
        applied after a k-limited reduction (unlike select, there is nothing
        left to refine), so such plans are rejected rather than silently
        answering the wrong question."""
        from geomesa_tpu.index.scan import _haversine_f32

        if plan.residual_host is not None or plan.candidate_slices is not None:
            raise ValueError(
                "distributed knn needs a device-exact plan (host residuals "
                "cannot refine a k-limited result)")
        rkey, rfn, boxes, windows, rparams = self._stage(plan)
        # ≥2k margin: f32 distance rounding can swap membership right at the
        # k-th boundary, so over-fetch and re-rank the margin in f64 on host
        # (same discipline as process/knn._exact_rerank)
        m_cap = min(max(32, 1 << (max(0, 2 * k - 1)).bit_length()),
                    self.sharded.n_padded)
        key = ("knn", plan.primary_kind, plan.windows is not None, rkey, m_cap)

        def build():
            def step(cols, boxes, windows, rparams, q):
                m = _build_mask(cols, plan.primary_kind, boxes, windows,
                                rfn, rparams)
                d = _haversine_f32(cols["xf"], cols["yf"], q[0], q[1])
                d = jnp.where(m, d, jnp.inf)
                vals, idxs = jax.lax.top_k(-d, m_cap)
                return -vals, idxs
            return jax.jit(step)

        fn = self._fn(key, build)
        q = self.sharded.replicated(np.array([x, y], dtype=np.float32))
        dists, idxs = fn(self.sharded.columns, boxes, windows, rparams, q)
        dists = np.asarray(dists)
        idxs = np.asarray(idxs)
        valid = np.isfinite(dists)
        idxs, dists = idxs[valid], dists[valid]
        if self.sharded.host_xy is not None and len(idxs):
            from geomesa_tpu.process.geo import haversine_m
            gx, gy = self.sharded.host_xy
            d = haversine_m(gx[idxs].astype(np.float64),
                            gy[idxs].astype(np.float64), x, y)
            order = np.argsort(d, kind="stable")[:k]
            # rank in f64, deliver f32 (the documented contract either path)
            return idxs[order], d[order].astype(np.float32)
        return idxs[:k], dists[:k]

    def mask(self, plan) -> np.ndarray:
        """Full boolean mask gathered to host (hydration path)."""
        rkey, rfn, boxes, windows, rparams = self._stage(plan)
        key = ("mask", plan.primary_kind, plan.windows is not None, rkey)

        def build():
            def step(cols, boxes, windows, rparams):
                return _build_mask(cols, plan.primary_kind, boxes, windows, rfn, rparams)
            return jax.jit(step)

        fn = self._fn(key, build)
        return np.asarray(fn(self.sharded.columns, boxes, windows, rparams))[: self.sharded.n]
