"""Spatial join: point-in-polygon over a device mesh.

≙ the reference's Spark spatial join surface — st_contains/st_intersects UDFs
(spark-jts SpatialRelationFunctions.scala:20-60) executed via spatially
partitioned sweepline joins (GeoMesaJoinRelation.scala:41-56). TPU-native
design (SURVEY.md §2.12 row 7, the BASELINE north-star workload):

  - the small side (polygons) broadcasts to every device as padded ring
    buffers: (P, V, 2) f32 vertex planes + per-polygon bbox prefilters
  - the big side (points) stays row-sharded on the mesh
  - a vmapped crossing-parity kernel computes the containment matrix
    blockwise; per-polygon hit counts psum-reduce over ICI

Precision: vertices and points recenter to the polygon-set centroid before the
f32 parity test, keeping relative error ~1e-7 of the domain size; ties on
polygon boundaries may differ from exact f64 (documented tolerance — the
host geom_numpy path is the exact oracle).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from geomesa_tpu.features import geometry as geo
from geomesa_tpu.filter import geom_numpy as gn


@dataclass
class PackedPolygons:
    """Broadcast-ready polygon buffers."""

    edges_a: np.ndarray    # (P, E, 2) f32 edge start vertices (recentered)
    edges_b: np.ndarray    # (P, E, 2) f32 edge end vertices
    valid: np.ndarray      # (P, E) bool real-edge mask
    bboxes: np.ndarray     # (P, 4) f32 [xmin, ymin, xmax, ymax] (original frame)
    center: np.ndarray     # (2,) f64 recentering offset
    n: int

    @classmethod
    def pack(cls, polygons: List[tuple]) -> "PackedPolygons":
        """polygons: list of (type_code, nested) Polygon/MultiPolygon literals."""
        all_edges = []
        bboxes = []
        for lit in polygons:
            rings = gn.polygon_rings(lit)
            e = np.concatenate([
                np.concatenate([r[:-1], r[1:]], axis=1) for r in rings])
            all_edges.append(e)
            bboxes.append(gn.literal_bbox(lit))
        emax = max(len(e) for e in all_edges)
        p = len(polygons)
        ea = np.zeros((p, emax, 2), dtype=np.float64)
        eb = np.zeros((p, emax, 2), dtype=np.float64)
        valid = np.zeros((p, emax), dtype=bool)
        for i, e in enumerate(all_edges):
            ea[i, : len(e)] = e[:, 0:2]
            eb[i, : len(e)] = e[:, 2:4]
            valid[i, : len(e)] = True
        bboxes = np.asarray(bboxes, dtype=np.float32)
        center = np.array([bboxes[:, [0, 2]].mean(), bboxes[:, [1, 3]].mean()], dtype=np.float64)
        ea -= center
        eb -= center
        return cls(ea.astype(np.float32), eb.astype(np.float32), valid,
                   bboxes, center, p)


def _pip_block(px, py, ea, eb, valid):
    """Points (N,) vs one polygon's edges (E,2): crossing parity (N,) bool."""
    x1, y1 = ea[:, 0], ea[:, 1]
    x2, y2 = eb[:, 0], eb[:, 1]
    pyv = py[:, None]
    pxv = px[:, None]
    cond = ((y1 > pyv) != (y2 > pyv)) & valid[None, :]
    # safe divide: cond guarantees y2 != y1 where it matters
    t = (pyv - y1) / jnp.where(y2 == y1, 1.0, y2 - y1)
    xint = x1 + t * (x2 - x1)
    crossings = cond & (pxv < xint)
    return jnp.sum(crossings, axis=1) % 2 == 1


def contains_matrix_kernel(px, py, mask, ea, eb, valid, bboxes, center):
    """(P,) per-polygon hit counts for row-sharded points.

    vmapped over polygons; each polygon applies its bbox prefilter (in the
    original frame) before the recentered parity test.
    """
    pxc = px - center[0]
    pyc = py - center[1]

    def per_poly(ea_p, eb_p, valid_p, bb):
        in_bb = (px >= bb[0]) & (px <= bb[2]) & (py >= bb[1]) & (py <= bb[3])
        inside = _pip_block(pxc, pyc, ea_p, eb_p, valid_p)
        return jnp.sum(inside & in_bb & mask)

    return jax.vmap(per_poly)(ea, eb, valid, bboxes)


def assign_kernel(px, py, mask, ea, eb, valid, bboxes, center):
    """(N,) first-matching polygon index per point (-1 = none)."""
    pxc = px - center[0]
    pyc = py - center[1]

    def per_poly(ea_p, eb_p, valid_p, bb):
        in_bb = (px >= bb[0]) & (px <= bb[2]) & (py >= bb[1]) & (py <= bb[3])
        return _pip_block(pxc, pyc, ea_p, eb_p, valid_p) & in_bb & mask

    hits = jax.vmap(per_poly)(ea, eb, valid, bboxes)          # (P, N)
    any_hit = jnp.any(hits, axis=0)
    first = jnp.argmax(hits, axis=0).astype(jnp.int32)
    return jnp.where(any_hit, first, -1)


class SpatialJoin:
    """Point-in-polygon join between a (sharded or local) point table and a
    polygon collection."""

    def __init__(self, polygons: List[tuple]):
        self.packed = PackedPolygons.pack(polygons)
        self._count_fn = jax.jit(contains_matrix_kernel)
        self._assign_fn = jax.jit(assign_kernel)

    def _bufs(self, replicate=None):
        pk = self.packed
        bufs = (pk.edges_a, pk.edges_b, pk.valid, pk.bboxes,
                pk.center.astype(np.float32))
        if replicate is not None:
            bufs = tuple(replicate(b) for b in bufs)
        return bufs

    def counts(self, px, py, mask=None, sharded=None) -> np.ndarray:
        """Per-polygon containment counts (the psum-reduced join aggregate)."""
        if mask is None:
            mask = jnp.ones(px.shape[0], dtype=bool)
        rep = sharded.replicated if sharded is not None else None
        ea, eb, valid, bboxes, center = self._bufs(rep)
        out = self._count_fn(px, py, mask, ea, eb, valid, bboxes, center)
        return np.asarray(out)

    def assign(self, px, py, mask=None, sharded=None) -> np.ndarray:
        """Per-point polygon assignment (-1 = no polygon) — the join's
        row-level output (st_contains join column)."""
        if mask is None:
            mask = jnp.ones(px.shape[0], dtype=bool)
        rep = sharded.replicated if sharded is not None else None
        ea, eb, valid, bboxes, center = self._bufs(rep)
        out = self._assign_fn(px, py, mask, ea, eb, valid, bboxes, center)
        return np.asarray(out)
