"""Device refine kernel for extent×extent join candidate pairs.

≙ the compute half of the reference's partition join: GeoMesaJoinRelation
evaluates the JTS predicate per candidate pair *inside the executors*
(/root/reference/geomesa-spark/geomesa-spark-sql/src/main/scala/org/
locationtech/geomesa/spark/GeoMesaJoinRelation.scala:41-56). Here the
executors are TPU chips: each candidate pair (left geometry, right geometry)
evaluates the INTERSECTS predicate in f32 with certified error bands —
certain-hit / certain-miss decisions are exact, and only the uncertain
sliver (pairs within ~1e-5 deg of touching) refines on the host in f64.

Data layout: geometries are ragged, devices want fixed shapes — so each
side's *unique* geometries become one padded segment table ``(G, S, 4)``
(S = pow2 of the max boundary-segment count) plus per-geometry segment
counts, uploaded ONCE; the pair lists are just int32 index vectors into
those tables, and the kernel gathers. Pairs are chunked to a fixed pow2
dispatch shape so one compiled program serves any pair count.

Intersects logic per pair, all band-certified:
  hit  = any boundary-segment pair certainly crosses
         OR (right is polygonal AND left's first vertex certainly inside)
         OR (left is polygonal AND right's first vertex certainly inside)
  miss = every segment pair certainly misses
         AND (right not polygonal OR left's first vertex certainly outside)
         AND (left not polygonal OR right's first vertex certainly outside)
  else uncertain → host exact refine (filter/geom_batch).
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from geomesa_tpu.features import geometry as geo
from geomesa_tpu.filter import geom_batch

# largest per-geometry boundary segment count the device path accepts;
# pairs involving bigger geometries refine on host (they are rare and one
# giant geometry would inflate every pair's padded shape)
MAX_SEGMENTS = 512
# pair-chunk dispatch shape: bounded so the (chunk, Ls, Rs) band
# intermediates stay well under HBM limits for the largest tier combo
_CHUNK_BUDGET = 1 << 26


def _pow2(n: int) -> int:
    return max(1, 1 << max(0, int(n) - 1).bit_length())


def padded_segment_table(arr: geo.GeometryArray, ids: np.ndarray
                         ) -> Optional[Tuple[np.ndarray, np.ndarray,
                                             np.ndarray, np.ndarray]]:
    """((G, S, 4) f32 padded segments, (G,) int32 counts, (G,) bool
    polygonal, (G,) bool single-part) for the selected geometries, or None
    when any geometry is segment-free (points) or exceeds MAX_SEGMENTS —
    callers fall back to the host refine.

    ``single-part`` drives the miss certification: "first vertex certainly
    outside + no boundary crossing ⇒ disjoint" is only sound for a
    CONNECTED geometry (a polygon's holes don't break connectivity of the
    filled region, but a MULTI* geometry's disconnected parts do — a
    non-first part could sit wholly inside the other geometry).
    """
    ids = np.asarray(ids, dtype=np.int64)
    segs, fid = geom_batch.build_segments(arr, ids)
    counts = np.bincount(fid, minlength=len(ids)).astype(np.int32)
    if len(ids) == 0 or counts.min() == 0 or counts.max() > MAX_SEGMENTS:
        return None
    s_cap = _pow2(int(counts.max()))
    g_cap = _pow2(len(ids))  # pow2 geometry axis: stable jit signatures
    out = np.zeros((g_cap, s_cap, 4), dtype=np.float32)
    pos = np.arange(len(fid)) - np.repeat(
        np.cumsum(counts) - counts, counts)
    out[fid, pos] = segs.astype(np.float32)
    cnt = np.zeros(g_cap, dtype=np.int32)
    cnt[: len(ids)] = counts
    poly = np.zeros(g_cap, dtype=bool)
    poly[: len(ids)] = np.isin(arr.type_codes[ids],
                               (geo.POLYGON, geo.MULTIPOLYGON))
    single = np.zeros(g_cap, dtype=bool)
    single[: len(ids)] = (arr.geom_offsets[ids + 1]
                          - arr.geom_offsets[ids]) == 1
    return out, cnt, poly, single


def _band_core(ls, lc, lpoly, lsingle, rs, rc, rpoly, rsingle):
    """Shared traced body: padded pair segments → (certain_hit, uncertain).

    ls: (P, Ls, 4) f32   lc: (P,) int32   lpoly/lsingle: (P,) bool
    rs: (P, Rs, 4) f32   rc: (P,) int32   rpoly/rsingle: (P,) bool
    Invalid (padded) segments are masked out of both the hit and the
    uncertainty reductions, so padding never flips a verdict.

    Miss certification requires connectivity: "first vertex certainly
    outside + every boundary pair certainly misses ⇒ disjoint" holds only
    for single-part geometries (a MULTI* part other than the first could
    sit wholly inside the other side without any crossing), so multi-part
    pairs that aren't certain hits classify as uncertain → exact host
    refine.
    """
    import jax.numpy as jnp

    from geomesa_tpu.index.scan import _pip_band, _segpair_band

    Ls = ls.shape[1]
    Rs = rs.shape[1]
    lv = jnp.arange(Ls, dtype=jnp.int32)[None, :] < lc[:, None]   # (P, Ls)
    rv = jnp.arange(Rs, dtype=jnp.int32)[None, :] < rc[:, None]   # (P, Rs)
    ax, ay, bx, by = ls[..., 0], ls[..., 1], ls[..., 2], ls[..., 3]
    cx, cy, dx, dy = rs[..., 0], rs[..., 1], rs[..., 2], rs[..., 3]

    hit_p, miss_p = _segpair_band(
        ax[:, :, None], ay[:, :, None], bx[:, :, None], by[:, :, None],
        cx[:, None, :], cy[:, None, :], dx[:, None, :], dy[:, None, :])
    pv = lv[:, :, None] & rv[:, None, :]
    any_hit = jnp.any(hit_p & pv, axis=(1, 2))
    all_miss = jnp.all(miss_p | ~pv, axis=(1, 2))

    # _pip_band broadcasts (P, 1) query points against (P, E) edges and
    # reduces the edge axis → (P,) verdicts
    l_in, l_out = _pip_band(ax[:, 0:1], ay[:, 0:1], cx, cy, dx, dy,
                            evalid=rv)
    r_in, r_out = _pip_band(cx[:, 0:1], cy[:, 0:1], ax, ay, bx, by,
                            evalid=lv)

    hit = any_hit | (rpoly & l_in) | (lpoly & r_in)
    miss = (all_miss
            & (~rpoly | (l_out & lsingle))
            & (~lpoly | (r_out & rsingle)))
    return hit, ~hit & ~miss


_PAIR_JIT = None


def _pair_fn():
    global _PAIR_JIT
    if _PAIR_JIT is None:
        import jax
        import jax.numpy as jnp

        def run(lsegs, lcnt, lpoly, lsingle, redges, rcnt, rpoly, rsingle,
                pl, pr):
            # gather per-pair geometry rows; -1 pads clamp to row 0 and are
            # masked by valid=False
            valid = pl >= 0
            pl = jnp.clip(pl, 0, lsegs.shape[0] - 1)
            pr = jnp.clip(pr, 0, redges.shape[0] - 1)
            hit, unc = _band_core(lsegs[pl], lcnt[pl], lpoly[pl],
                                  lsingle[pl], redges[pr], rcnt[pr],
                                  rpoly[pr], rsingle[pr])
            # bit-packed verdicts: the result readback shrinks 8x, which is
            # what the delivered latency is made of on a tunnel-attached chip
            return (jnp.packbits(hit & valid), jnp.packbits(unc & valid))

        _PAIR_JIT = jax.jit(run)
    return _PAIR_JIT


def _chunk_size(s_l: int, s_r: int) -> int:
    ch = int(np.clip(_CHUNK_BUDGET // max(1, s_l * s_r), 1024, 1 << 20))
    # the packed-verdict concatenation in PreparedPairRefine requires every
    # chunk to fill whole bytes — keep ch a multiple of 8 regardless of how
    # the budget constants evolve
    return max(8, ch & ~7)


class PreparedPairRefine:
    """Pair refine with every input staged on device (the prepared-query
    pattern applied to the join: geometry tables + chunked pair index
    vectors upload once, re-dispatches pay only kernel time + the packed
    verdict readback)."""

    def __init__(self, d_l, d_r, d_pairs, n: int):
        self._d_l = d_l
        self._d_r = d_r
        self._d_pairs = d_pairs
        self.n = n

    def dispatch(self):
        """Async: ONE (2, P/8) packed device array (row 0 = hits, row 1 =
        uncertain) — a single readback syncs the whole refine, so the
        delivered latency floors at one round trip, not one per chunk."""
        import jax.numpy as jnp

        if not self._d_pairs:
            return jnp.zeros((2, 0), jnp.uint8)
        fn = _pair_fn()
        outs = [fn(*self._d_l, *self._d_r, pl, pr)
                for pl, pr in self._d_pairs]
        return jnp.stack([jnp.concatenate([h for h, _ in outs]),
                          jnp.concatenate([u for _, u in outs])])

    def __call__(self) -> Tuple[np.ndarray, np.ndarray]:
        if self.n == 0:
            return np.zeros(0, dtype=bool), np.zeros(0, dtype=bool)
        packed = np.asarray(self.dispatch())
        hit = np.unpackbits(packed[0])[: self.n]
        unc = np.unpackbits(packed[1])[: self.n]
        return hit.astype(bool), unc.astype(bool)


def prepare_refine(left: geo.GeometryArray, right: geo.GeometryArray,
                   li: np.ndarray, rj: np.ndarray
                   ) -> Optional[PreparedPairRefine]:
    """Stage an INTERSECTS pair-refine on device, or None when the workload
    doesn't fit the device path (point/oversized geometries)."""
    try:
        import jax.numpy as jnp
    except Exception:  # pragma: no cover - jax is a hard dep in practice
        return None
    n = len(li)
    if n == 0:  # a legitimately empty join is not "unsupported"
        return PreparedPairRefine([], [], [], 0)
    ul, inv_l = np.unique(li, return_inverse=True)
    ur, inv_r = np.unique(rj, return_inverse=True)
    lt = padded_segment_table(left, ul)
    rt = padded_segment_table(right, ur)
    if lt is None or rt is None:
        return None
    d_l = [jnp.asarray(a) for a in lt]
    d_r = [jnp.asarray(a) for a in rt]
    ch = _chunk_size(lt[0].shape[1], rt[0].shape[1])
    d_pairs = []
    for s in range(0, n, ch):
        e = min(n, s + ch)
        pl = np.full(ch, -1, dtype=np.int32)
        pr = np.zeros(ch, dtype=np.int32)
        pl[: e - s] = inv_l[s:e]
        pr[: e - s] = inv_r[s:e]
        d_pairs.append((jnp.asarray(pl), jnp.asarray(pr)))
    return PreparedPairRefine(d_l, d_r, d_pairs, n)


def device_refine(left: geo.GeometryArray, right: geo.GeometryArray,
                  li: np.ndarray, rj: np.ndarray
                  ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Evaluate INTERSECTS for candidate pairs on the device.

    Returns (certain_hit bool (P,), uncertain bool (P,)) — uncertain pairs
    need the host's exact f64 refine. None when the workload shape doesn't
    fit the device path (point geometries / oversized geometries); callers
    fall back to the host refine for everything.
    """
    prep = prepare_refine(left, right, li, rj)
    return None if prep is None else prep()


def mesh_join_pairs(mesh, left: geo.GeometryArray, right: geo.GeometryArray,
                    li: np.ndarray, rj: np.ndarray):
    """Distributed pair refine over a device mesh: the pair axis shards
    across devices, the (small) geometry segment tables replicate — the
    broadcast-small-side spatial join of SURVEY §2.12 row 7 — and each
    device evaluates its pair slice with the same band kernel. Returns
    (certain_hit (P,), uncertain (P,), per_device_hits (D,)); the hit
    counts come back via a psum-lowered sharded sum so the merge rides ICI,
    not the host.

    None when the workload doesn't fit the device path (point/oversized
    geometries), mirroring ``device_refine``.
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    n_dev = mesh.devices.size
    n = len(li)
    if n == 0:
        return (np.zeros(0, dtype=bool), np.zeros(0, dtype=bool),
                np.zeros(n_dev, dtype=np.int32))
    ul, inv_l = np.unique(li, return_inverse=True)
    ur, inv_r = np.unique(rj, return_inverse=True)
    lt = padded_segment_table(left, ul)
    rt = padded_segment_table(right, ur)
    if lt is None or rt is None:
        return None
    rows = NamedSharding(mesh, P("rows"))
    repl = NamedSharding(mesh, P())
    d_l = [jax.device_put(a, repl) for a in lt]
    d_r = [jax.device_put(a, repl) for a in rt]
    fn = _mesh_fn(mesh, n_dev)

    # chunk the pair axis like device_refine: per-device band intermediates
    # stay within _CHUNK_BUDGET instead of scaling with the join size
    ch = _chunk_size(lt[0].shape[1], rt[0].shape[1]) * n_dev
    hits, uncs = [], []
    per_dev = np.zeros(n_dev, dtype=np.int64)
    for s in range(0, n, ch):
        e = min(n, s + ch)
        # pad to the FULL chunk width (multi-chunk) or a pow2 multiple of
        # n_dev (single chunk): remainder-sized shapes would trigger a fresh
        # XLA trace per distinct tail (-1 sentinels make the slop free), so
        # chunks share compiled programs
        if n > ch:
            n_pad = ch
        else:
            m = (e - s + n_dev - 1) // n_dev
            n_pad = n_dev * (1 << max(0, (m - 1).bit_length()))
        pl = np.full(n_pad, -1, dtype=np.int32)
        pr = np.zeros(n_pad, dtype=np.int32)
        pl[: e - s] = inv_l[s:e]
        pr[: e - s] = inv_r[s:e]
        hit, unc, pd = fn(*d_l, *d_r, jax.device_put(pl, rows),
                          jax.device_put(pr, rows))
        hits.append(np.asarray(hit)[: e - s])
        uncs.append(np.asarray(unc)[: e - s])
        per_dev += np.asarray(pd)
    return np.concatenate(hits), np.concatenate(uncs), per_dev


_MESH_JITS: dict = {}


def _mesh_fn(mesh, n_dev: int):
    """Jitted mesh pair kernel, cached per device set (jit's own cache is
    keyed on callable identity — a fresh closure per call would retrace and
    recompile every invocation, 10-90s each through a tunnel)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    key = tuple(int(d.id) for d in mesh.devices.flat)
    if key in _MESH_JITS:
        return _MESH_JITS[key]
    rows = NamedSharding(mesh, P("rows"))
    repl = NamedSharding(mesh, P())

    def run(lsegs, lcnt, lpoly, lsingle, redges, rcnt, rpoly, rsingle,
            pl, pr):
        valid = pl >= 0
        pl = jnp.clip(pl, 0, lsegs.shape[0] - 1)
        pr = jnp.clip(pr, 0, redges.shape[0] - 1)
        hit, unc = _band_core(lsegs[pl], lcnt[pl], lpoly[pl], lsingle[pl],
                              redges[pr], rcnt[pr], rpoly[pr], rsingle[pr])
        hit = hit & valid
        unc = unc & valid
        # per-device hit counts: a sharded segment-sum XLA lowers to local
        # sums + an ICI gather (the FeatureReducer merge as a collective)
        per_dev = jnp.sum(hit.reshape(n_dev, -1), axis=1)
        return hit, unc, per_dev

    fn = jax.jit(run, out_shardings=(rows, rows, repl))
    _MESH_JITS[key] = fn
    return fn
