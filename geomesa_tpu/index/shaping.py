"""Query result shaping: projection/transforms, sort, limit, reprojection.

≙ the client-side shaping chain of the reference's QueryPlanner.runQuery
(/root/reference/geomesa-index-api/src/main/scala/org/locationtech/geomesa/
index/planning/QueryPlanner.scala:56-94) and QueryRunner's query
normalization (planning/QueryRunner.scala:185-304): transform definitions
become a projected feature type, sort + max-features trim the result, and
reprojection maps output geometries to the requested CRS.

TPU shaping: sort keys and limits apply to ROW INDICES before hydration (a
sorted+limited query never materializes more than `limit` features), and
transform expressions evaluate vectorized over whole columns via the
converter expression DSL (convert/expression.py) — there is no per-feature
path anywhere.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple, Union

import numpy as np

from geomesa_tpu.features.sft import SimpleFeatureType
from geomesa_tpu.features.table import FeatureTable, StringColumn

SortSpec = Union[str, Sequence[str]]


def _sort_key(table: FeatureTable, attr: str, rows: np.ndarray):
    """(key array ascending-sorts like the attribute, descending flag)."""
    desc = attr.startswith("-")
    name = attr[1:] if desc else attr
    col = table.columns[name] if name in table.columns else None
    if col is None:
        raise ValueError(f"No such sort attribute: {name}")
    if isinstance(col, StringColumn):
        codes = col.codes[rows]
        if list(col.vocab) != sorted(col.vocab):
            # vocab not in lexicographic order (merged/streamed tables):
            # rank-map the codes so integer order == string order
            rank = np.empty(len(col.vocab), dtype=np.int64)
            rank[np.argsort(np.asarray(col.vocab, dtype=object))] = \
                np.arange(len(col.vocab))
            codes = rank[codes]
        key = codes.astype(np.int64)
    else:
        key = np.asarray(col)[rows]
        if key.dtype == object or key.dtype.kind not in "biufM":
            raise ValueError(f"Cannot sort by {name} (dtype {key.dtype})")
    if desc:
        key = -key.astype(np.float64) if key.dtype.kind == "f" else -key.astype(np.int64)
    return key


def shape_rows(table: FeatureTable, rows: np.ndarray,
               sort: Optional[SortSpec] = None,
               limit: Optional[int] = None) -> np.ndarray:
    """Apply sort (attr | '-attr' | list, stable lexicographic) and limit to
    matching row indices BEFORE hydration (≙ sort + maxFeatures hints)."""
    if sort is not None:
        specs = [sort] if isinstance(sort, str) else list(sort)
        keys = [_sort_key(table, s, rows) for s in specs]
        # np.lexsort sorts by the LAST key first; our specs are major-first
        order = np.lexsort(tuple(reversed(keys + [rows])))
        rows = rows[order]
    if limit is not None:
        rows = rows[: int(limit)]
    return rows


def shape_local(table: FeatureTable,
                sort: Optional[SortSpec] = None,
                limit: Optional[int] = None) -> np.ndarray:
    """Sort/limit order over ALL rows of an already-hydrated table (the
    merged main+delta sub-result); returns local row indices."""
    return shape_rows(table, np.arange(len(table), dtype=np.int64),
                      sort, limit)


_DTYPE_TO_TYPE = {
    "i4": "Int", "i8": "Long", "f4": "Float", "f8": "Double", "b1": "Boolean",
}


def _infer_type(arr) -> str:
    if isinstance(arr, StringColumn):
        return "String"
    a = np.asarray(arr)
    if a.dtype == object:
        # json-path / mixed expression outputs: ONE pass classifies the
        # column — clean numeric promotes, anything mixed/None-bearing
        # becomes dictionary strings
        all_bool = all_int = all_num = bool(len(a))
        for v in a:
            if isinstance(v, bool):
                all_int = all_num = False
            elif isinstance(v, (int, np.integer)):
                all_bool = False
            elif isinstance(v, (float, np.floating)):
                all_bool = all_int = False
            else:
                return "String"
            if not (all_bool or all_num):
                return "String"
        if all_bool:
            return "Boolean"
        if all_int:
            return "Long"
        if all_num:
            return "Double"
        return "String"
    return _DTYPE_TO_TYPE.get(a.dtype.str[1:], "Double")


def transform_table(table: FeatureTable, transforms: Sequence[str],
                    type_name: Optional[str] = None) -> FeatureTable:
    """Project/derive attributes (≙ setQueryTransforms,
    QueryPlanner.scala:185-235): each entry is either an attribute name or
    ``out=expression`` with the converter expression DSL operating on
    ``$attr`` field references — evaluated vectorized over the whole column
    set."""
    from geomesa_tpu.convert.expression import parse_expression

    n = len(table)
    fields = {}
    for name, col in table.columns.items():
        if isinstance(col, StringColumn):
            fields[name] = np.asarray(col.decode(np.arange(n)), dtype=object)
        elif hasattr(col, "coords"):        # GeometryArray: ref only
            fields[name] = col
        else:
            fields[name] = np.asarray(col)

    out_cols = {}
    spec_parts: List[str] = []
    for t in transforms:
        if "=" in t:
            out_name, expr_src = (s.strip() for s in t.split("=", 1))
            expr = parse_expression(expr_src)
            val = expr.eval(fields, n)
            if np.ndim(val) == 0:
                val = np.full(n, val)
            t = _infer_type(val)
            if t == "String" and getattr(val, "dtype", None) == object:
                # stringify mixed/None-bearing outputs for the dictionary
                val = np.asarray(["" if v is None else str(v) for v in val],
                                 dtype=object)
            out_cols[out_name] = val
            spec_parts.append(f"{out_name}:{t}")
        else:
            attr = table.sft.attribute(t)
            out_cols[t] = table.columns[t]
            spec_parts.append(attr.to_spec())
    sft = SimpleFeatureType.from_spec(type_name or table.sft.name,
                                      ",".join(spec_parts))
    return FeatureTable.build(sft, out_cols, fids=table._fids)


def reproject_table(table: FeatureTable, crs) -> FeatureTable:
    """Output geometries mapped to ``crs`` (≙ QueryRunner reprojection,
    planning/QueryRunner.scala:293); attribute columns pass through."""
    from geomesa_tpu.features.crs import reproject_geometry

    geom_attr = table.sft.geometry_attribute
    if geom_attr is None:
        return table
    cols = dict(table.columns)
    cols[geom_attr.name] = reproject_geometry(
        table.geometry(), "EPSG:4326", crs)
    return FeatureTable.build(table.sft, cols, fids=table._fids)
