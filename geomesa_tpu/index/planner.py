"""Query planning & execution (≙ reference index.planning package:
QueryPlanner.scala:36, FilterSplitter, StrategyDecider).

Flow (mirrors call stack SURVEY.md §3.3):
  1. parse/normalize the filter
  2. ask each index for a strategy + heuristic cost; pick the cheapest
     (CostBasedStrategyDecider:140-168 moral equivalent — stats integration
     arrives with the stats subsystem)
  3. execute: fused device mask scan → (count | nonzero-select) → host
     boundary/residual refinement → hydrate rows

Exactness: results are always exact. The device scan is a superset prune;
definite matches come from strict (cell-interior) masks, and only the
boundary band re-evaluates in f64 on the host.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Union

import numpy as np

from geomesa_tpu import trace as _trace
from geomesa_tpu.features.table import FeatureTable
from geomesa_tpu.filter.evaluate import evaluate as _evaluate
from geomesa_tpu.filter.evaluate import evaluate_at as _evaluate_at
from geomesa_tpu.filter import ir
from geomesa_tpu.filter.parser import parse_ecql
from geomesa_tpu.index.api import IndexScanPlan, QueryResult, UnionScanPlan
from geomesa_tpu.index import prune as _prune
from geomesa_tpu.serve.resilience import deadline as _rdl

_SELECT_CAP = 1 << 16
# select-capacity tiers: each distinct capacity compiles its own packed
# select kernel (seconds of XLA time through the tunnel), so capacity hints
# quantize UP to a coarse tier instead of the exact power of two
_SELECT_TIERS = (1 << 10, 1 << 13, _SELECT_CAP, 1 << 19, 1 << 22)


def _select_tier(capacity) -> int:
    if capacity is None:
        return _SELECT_CAP
    for t in _SELECT_TIERS:
        if capacity <= t:
            return t
    return 1 << max(0, (int(capacity) - 1)).bit_length()


def _pad_pow2(arr: np.ndarray, fill: int) -> np.ndarray:
    size = max(1, 1 << max(0, (len(arr) - 1)).bit_length())
    out = np.full(size, fill, dtype=np.int32)
    out[: len(arr)] = arr
    return out


class QueryPlanner:
    """Planner + executor for one feature type."""

    def __init__(self, sft, table: FeatureTable, indexes: List[object],
                 stats=None, interceptors: Optional[list] = None,
                 audit=None, timeout_ms: Optional[float] = None):
        self.sft = sft
        self.table = table
        self.indexes = indexes
        self.stats = stats  # GeoMesaStats for cost-based strategy selection
        self.interceptors = interceptors if interceptors is not None else []
        self.audit = audit              # AuditWriter | None
        self.timeout_ms = timeout_ms    # cooperative deadline (guards.Deadline)
        self._fid_map: Optional[Dict[str, int]] = None

    # -- fid lookup (≙ IdIndex direct row lookup) ---------------------------

    @property
    def fid_map(self) -> Dict[str, int]:
        if self._fid_map is None:
            self._fid_map = {fid: i for i, fid in enumerate(self.table.fids)}
        return self._fid_map

    # -- planning -----------------------------------------------------------

    def plan(self, f: Union[str, ir.Filter]) -> IndexScanPlan:
        if not _trace.enabled():
            return self._plan(f)
        t0 = time.perf_counter()
        try:
            return self._plan(f)
        finally:
            _trace.record("plan", "plan", time.perf_counter() - t0)

    def _plan(self, f: Union[str, ir.Filter]) -> IndexScanPlan:
        if isinstance(f, str):
            f = parse_ecql(f)
        for ic in self.interceptors:
            f = ic.rewrite(f, self.sft)  # ≙ QueryInterceptor.rewrite
        if isinstance(f, ir.FidFilter):
            return IndexScanPlan(None, "fid", full_filter=f, cost=0.5,
                                 explain={"index": "id", "fids": f.fids})
        if not self.indexes:
            raise ValueError(f"No indexes for {self.sft.name}")
        plans = [p for p in (idx.plan(f) for idx in self.indexes) if p is not None]
        if self.stats is not None and self.stats.total > 0 and len(plans) > 1:
            # cost-based strategy selection (≙ CostBasedStrategyDecider,
            # StrategyDecider.scala:140-168): price each strategy by the
            # estimated rows its PRIMARY constraints leave to scan; the
            # heuristic cost breaks ties.
            est = self.stats.estimator
            n = self.stats.total

            def priced(p):
                if p.empty:
                    return (0.0, p.cost)
                if p.candidate_slices is not None:
                    # attribute slices: the scanned row count is exact
                    return (float(p.n_candidates), p.cost)
                sel = 1.0
                boxes = p.explain.get("boxes")
                if p.boxes_loose is not None and boxes:
                    s = est.spatial_selectivity(boxes)
                    if s is not None:
                        sel *= s
                intervals = p.explain.get("intervals")
                if p.windows is not None and intervals:
                    s = est.temporal_selectivity(intervals)
                    if s is not None:
                        sel *= s
                # per-curve cover quality: an S2 cover scans ~1.1x the true
                # rows where z-covers scan ~1.02x (measured, curves/s2.py),
                # so equal selectivities must not tie
                slop = getattr(p.index, "cover_slop", 1.0)
                return (sel * n * slop, p.cost)

            chosen = min(plans, key=priced)
        else:
            chosen = min(plans, key=lambda p: p.cost)
        if isinstance(f, ir.Or) and chosen.residual_host is not None:
            # OR → multi-strategy (≙ FilterSplitter.getQueryOptions OR
            # expansion): when every branch plans with real primary
            # constraints, per-branch scans + row-set union beat the
            # union-boxes prefilter + host residual the single plan needs
            union = self._union_plan(f)
            if union is not None:
                chosen = union
        for ic in self.interceptors:   # ≙ query guards veto (QueryPlanner:148)
            msg = ic.guard(chosen, f, self.sft)
            if msg:
                from geomesa_tpu.index.guards import QueryGuardError
                raise QueryGuardError(msg)
        return chosen

    def _union_plan(self, f: ir.Or) -> Optional[UnionScanPlan]:
        """Per-branch plans for an OR filter, or None when any branch would
        degenerate to an unconstrained scan (then the single superset plan
        wins). Branch count is capped like the reference's DNF expansion."""
        if len(f.children) > 8:
            return None
        branches = []
        cost = 0.0
        for c in f.children:
            plans = [p for p in (idx.plan(c) for idx in self.indexes)
                     if p is not None]
            if not plans:
                return None
            bp = min(plans, key=lambda p: p.cost)
            if bp.empty:
                continue
            if bp.primary_kind == "none" and bp.candidate_slices is None:
                return None  # unconstrained branch: union buys nothing
            branches.append((c, bp))
            cost += bp.cost
        return UnionScanPlan(
            branches=branches, full_filter=f, cost=cost,
            empty=not branches,
            explain={"index": "union",
                     "strategies": [p.explain.get("index")
                                    for _, p in branches]})

    def explain(self, f: Union[str, ir.Filter], analyze: bool = False,
                auths=None) -> Dict[str, object]:
        """Hierarchical plan description (≙ Explainer / CLI explain). The
        ``trace`` key carries the span tree of the dry-run (plan + range
        decomposition — no scan executes), so explain shows where planning
        time goes, not just what the plan is.

        ``analyze=True`` (≙ EXPLAIN ANALYZE) additionally EXECUTES the
        plan's count path inside the same trace and annotates each span in
        the returned tree with its device ms and cache provenance, plus an
        ``analyze`` summary: rows scanned/matched, device-vs-host split,
        per-stage self times."""
        with _trace.trace("explain", type=self.sft.name) as t:
            plan = self.plan(f)
            blocks = self._pruned_blocks(plan)  # surface the pruning decision
            n = None
            if analyze:
                plan_x = self._apply_auths(plan, auths)
                n = self._count(
                    plan_x, f if isinstance(f, ir.Filter) else parse_ecql(f),
                    auths)
        out = dict(plan.explain)
        if t is not None:
            tdict = t.to_dict()
            if analyze:
                from geomesa_tpu.obs import attrib as _oattrib
                _oattrib.annotate_tree(tdict["root"])
            out["trace"] = tdict
        out["scan"] = "range-pruned" if blocks is not None else "full-mask"
        out.update({
            "type": self.sft.name,
            "strategy": plan.primary_kind,
            "cost": plan.cost,
            "empty": plan.empty,
            "n_boxes": 0 if plan.boxes_loose is None else len(plan.boxes_loose),
            "n_windows": 0 if plan.windows is None else len(plan.windows),
        })
        # how the serving index was built (the GET /progress history for
        # this type + the owning index's per-stage timings): a slow query
        # on a freshly-built index explains against its build, not a void
        if plan.index is not None:
            build: Dict[str, object] = {}
            stages = getattr(plan.index, "build_stages", None)
            if stages:
                build["stages"] = dict(stages)
            from geomesa_tpu.obs.profiling import PROGRESS
            phases = PROGRESS.recent(type_name=self.sft.name, limit=8)
            if phases:
                build["recent_phases"] = phases
            if build:
                out["build"] = build
        if analyze and t is not None:
            stages = t.self_times_ms()
            device_ms = stages.get("device_scan", 0.0) \
                + stages.get("device_wait", 0.0)
            out["analyze"] = {
                "executed": True,
                "rows_matched": int(n) if n is not None else None,
                "rows_scanned": (len(blocks) * _prune.BLOCK_SIZE
                                 if blocks is not None else len(self.table)),
                "duration_ms": round(t.duration_ms, 3),
                "device_ms": round(device_ms, 3),
                "host_ms": round(max(0.0, t.duration_ms - device_ms), 3),
                "stages_ms": {k: round(v, 3) for k, v in stages.items()},
                # direct-path execution never serves from the scheduler's
                # plan/cover caches; the store-level explain overlays the
                # live scheduler's provenance when one is running
                "provenance": {"plan": "fresh",
                               "cover": "fresh" if blocks is not None
                               else "n/a"},
            }
        return out

    # -- visibility enforcement (≙ VisibilityFilter, geomesa-security) -------

    def _apply_auths(self, plan: IndexScanPlan, auths) -> IndexScanPlan:
        """Fold an auths-derived visibility mask into the plan's device
        residual: each DISTINCT visibility expression evaluates once on the
        host; the device tests dictionary-code membership."""
        if auths is None or self.table.visibility is None or plan.empty \
                or plan.explain.get("__vis_applied__"):
            return plan
        if isinstance(plan, UnionScanPlan):
            # branches fold the auths mask individually at execution time
            return plan
        import dataclasses

        import jax.numpy as jnp

        from geomesa_tpu.security.visibility import allowed_codes

        # the __vis_applied__ marker lands in a COPIED explain dict on the
        # replaced plan only: dataclasses.replace shares the explain dict, so
        # marking the original would make a reused plan (prepared query,
        # plan cache, union branch) silently skip the auths fold on its next
        # execution — exactly the privileged-plan leak the marker guards
        # against double-folding, inverted
        marked = dict(plan.explain, __vis_applied__=True)
        vocab = self.table.visibility.vocab
        allowed = allowed_codes(vocab, auths)
        if len(allowed) == len(vocab):
            # every expression visible — no mask needed, but still mark the
            # handed-back plan so a re-apply is a no-op
            return dataclasses.replace(plan, explain=marked)
        if len(allowed) == 0:
            return dataclasses.replace(plan, empty=True, explain=marked)
        padded = _pad_pow2(allowed, fill=-1)
        key, params, fn = plan.residual_device or ("none", [], None)
        i = len(params)

        def fn2(cols, p, fn=fn, i=i):
            m = jnp.any(cols["__vis__"][:, None] == p[i][None, :], axis=1)
            return m if fn is None else (m & fn(cols, p))

        return dataclasses.replace(
            plan, explain=marked,
            residual_device=(f"vis{len(padded)}&({key})",
                             list(params) + [padded], fn2))

    def _fid_vis_filter(self, rows: np.ndarray, auths) -> np.ndarray:
        if auths is None or self.table.visibility is None or len(rows) == 0:
            return rows
        from geomesa_tpu.security.visibility import allowed_codes
        allowed = allowed_codes(self.table.visibility.vocab, auths)
        return rows[np.isin(self.table.visibility.codes[rows], allowed)]

    # -- range pruning -------------------------------------------------------

    def _pruned_blocks(self, plan: IndexScanPlan):
        """Candidate gather-blocks for a plan (cached on the plan), or None
        when the full-table fused mask is the better scan. ≙ choosing ranged
        scans over a full-table scan (QueryProperties.BlockFullTableScans)."""
        from geomesa_tpu import config
        if not config.PRUNE_ENABLED.get():
            return None
        if plan.blocks is False:
            # per-request deadline checkpoint: the range decomposition is
            # the priciest host stage before device dispatch — a request
            # whose budget already lapsed must not start it
            _rdl.check_current("range_decompose")
            blocks = None
            if (not plan.empty and plan.index is not None
                    and plan.candidate_slices is None
                    and hasattr(plan.index, "candidate_blocks")):
                if _trace.enabled():
                    t0 = time.perf_counter()
                    blocks = plan.index.candidate_blocks(plan)
                    _trace.record("range_decompose", "range_decompose",
                                  time.perf_counter() - t0)
                else:
                    blocks = plan.index.candidate_blocks(plan)
            plan.blocks = blocks
        return plan.blocks

    # -- execution ----------------------------------------------------------

    def _write_audit(self, plan, f, plan_ms: float, scan_ms: float,
                     hits: int) -> None:
        if self.audit is None:
            return
        from geomesa_tpu.index.guards import QueryEvent
        self.audit.write(QueryEvent(
            type_name=self.sft.name, filter=str(f),
            ts_ms=int(time.time() * 1000), plan_time_ms=round(plan_ms, 3),
            scan_time_ms=round(scan_ms, 3), hits=hits,
            index=str(plan.explain.get("index", ""))))

    def prepare(self, f: Union[str, ir.Filter], auths=None) -> "PreparedQuery":
        """Plan once and stage all query constants on device; the returned
        handle re-executes without re-parsing, re-planning, or re-uploading
        (≙ a configured scan the reference would hand each tablet server;
        also the natural unit for pipelined dispatch).

        When this (filter shape, auths) has fused before, the recipe fast
        path (index/compiled.py) binds the new values straight into the
        compiled single-dispatch program — no planning, no range decompose,
        no per-constant uploads. The ordinary path registers each shape's
        outcome so its NEXT occurrence takes the fast path."""
        from geomesa_tpu.index import compiled as _fused
        f_ir = f if isinstance(f, ir.Filter) else parse_ecql(f)
        fp = _fused.fast_prepare(self, f_ir, auths)
        if fp is not None:
            return fp
        plan = self._apply_auths(self.plan(f_ir), auths)
        pq = PreparedQuery(self, plan, f_ir, auths)
        _fused.note_shape(self, plan, f_ir, auths, pq._fused)
        return pq

    def count(self, f: Union[str, ir.Filter], auths=None) -> int:
        from geomesa_tpu.index.guards import Deadline
        with _trace.trace("count", type=self.sft.name, filter=str(f)):
            dl = Deadline(self.timeout_ms)
            t0 = time.perf_counter()
            plan = self._apply_auths(self.plan(f), auths)
            plan_ms = (time.perf_counter() - t0) * 1000
            dl.check("plan")
            t1 = time.perf_counter()
            n = self._count(plan, f, auths)
            dl.check("scan")
            self._write_audit(plan, f, plan_ms,
                              (time.perf_counter() - t1) * 1000, n)
            return n

    def _count(self, plan: IndexScanPlan, f, auths) -> int:
        if plan.empty:
            return 0
        if isinstance(plan, UnionScanPlan):
            idx = plan.same_index_device_exact()
            if idx is not None:
                # fused OR-of-masks count: branch masks OR on device, one
                # scalar readback (branch overlaps dedup in the OR itself)
                import functools

                import jax.numpy as jnp
                masks = [idx.kernels.mask(
                    bp2.primary_kind, bp2.boxes_loose, bp2.windows,
                    bp2.residual_device)
                    for bp2 in (self._apply_auths(bp, auths)
                                for _, bp in plan.branches)]
                return int(jnp.sum(functools.reduce(
                    lambda a, b: a | b, masks)))
            return len(self._union_select(plan, auths))
        if plan.primary_kind == "fid":
            return len(self._fid_vis_filter(
                self._fid_rows(plan.full_filter), auths))
        from geomesa_tpu.index import compiled as _fused
        if plan.residual_host is None:
            # fully device-exact: one fused reduction, one roundtrip
            if plan.candidate_slices is not None:
                return plan.index.kernels.count_at(
                    plan.primary_kind, plan.boxes_loose, plan.windows,
                    plan.residual_device, plan.candidate_positions())
            fused = _fused.try_count(self, plan)
            if fused is not None:
                return fused
            blocks = self._pruned_blocks(plan)
            if blocks is not None:
                if len(blocks) == 0:
                    return 0
                return plan.index.kernels.count_blocks(
                    plan.primary_kind, plan.boxes_loose, plan.windows,
                    plan.residual_device, blocks, _prune.BLOCK_SIZE)
            return plan.index.kernels.count(
                plan.primary_kind, plan.boxes_loose, plan.windows,
                plan.residual_device)
        fused = _fused.try_count_refine(self, plan)
        if fused is not None:
            return fused
        fast = self._band_intersects_count(plan)
        if fast is not None:
            return fast
        return len(self.select_indices(
            f if isinstance(f, ir.Filter) else parse_ecql(f),
            plan=plan, auths=auths))

    def _band_intersects_count(self, plan) -> Optional[int]:
        """Device certainty-band count for the common extent query shape —
        a single polygon-INTERSECTS residual over a single-segment layer:
        the kernel classifies candidates as certain-hit / certain-miss /
        uncertain (f32 error bands), and only the uncertain sliver refines
        on host in exact f64. None when the shape doesn't apply."""
        res = plan.residual_host
        if not (isinstance(res, ir.Intersects) and plan.index is not None
                and plan.candidate_slices is None
                and plan.primary_kind == "bbox_overlap"):
            return None
        from geomesa_tpu.features import geometry as geo
        code = res.geometry[0]
        if code != geo.POLYGON:
            return None
        if not getattr(plan.index, "ensure_segment_columns", lambda: False)():
            return None
        blocks = self._pruned_blocks(plan)
        if blocks is None or len(blocks) == 0:
            return 0 if blocks is not None else None
        from geomesa_tpu.filter.geom_numpy import literal_segments
        edges = literal_segments(res.geometry).astype(np.float32)
        certain, unc = plan.index.kernels.intersects_band_blocks(
            plan.primary_kind, plan.boxes_loose, plan.windows,
            plan.residual_device, edges, blocks, _prune.BLOCK_SIZE)
        if unc is None:
            return None  # uncertainty overflow: full host refine instead
        if len(unc) == 0:
            return certain
        from geomesa_tpu.filter.geom_batch import batch_intersects
        with _trace.span("refine", kind="refine", rows=len(unc)):
            rows = plan.index.map_rows(unc)
            return certain + int(batch_intersects(
                self.table.geometry(), rows, res.geometry).sum())

    def select_indices(self, f: Union[str, ir.Filter],
                       plan: Optional[IndexScanPlan] = None,
                       auths=None, capacity: Optional[int] = None) -> np.ndarray:
        """Matching row indices (ascending) into the master table.

        ``capacity``: expected match-count hint — sized from a prior count it
        avoids the overflow-retry rescans (index/scan.py select)."""
        if plan is None:
            plan = self.plan(f)
        # "scan" umbrella: its SELF time is constant staging + host glue
        # (pad/upload, map_rows, sort) around the nested device/refine spans
        with _trace.span("scan", kind="scan"):
            plan = self._apply_auths(plan, auths)
            if plan.empty:
                return np.empty(0, dtype=np.int64)
            if isinstance(plan, UnionScanPlan):
                return self._union_select(plan, auths)
            if plan.primary_kind == "fid":
                return self._fid_vis_filter(
                    self._fid_rows(plan.full_filter), auths)
            if plan.candidate_slices is not None:
                idx, _ = plan.index.kernels.select_at(
                    plan.primary_kind, plan.boxes_loose, plan.windows,
                    plan.residual_device, plan.candidate_positions())
            else:
                from geomesa_tpu.index import compiled as _fused
                if plan.residual_host is None:
                    pos = _fused.try_select(self, plan, capacity)
                    if pos is not None:
                        return np.sort(plan.index.map_rows(pos))
                else:
                    rows = _fused.try_select_refine(self, plan, capacity)
                    if rows is not None:
                        return rows
                blocks = self._pruned_blocks(plan)
                if blocks is not None:
                    if len(blocks) == 0:
                        return np.empty(0, dtype=np.int64)
                    idx, _ = plan.index.kernels.select_blocks(
                        plan.primary_kind, plan.boxes_loose, plan.windows,
                        plan.residual_device, blocks, _prune.BLOCK_SIZE,
                        _select_tier(capacity))
                else:
                    idx, _ = plan.index.kernels.select(
                        plan.primary_kind, plan.boxes_loose, plan.windows,
                        plan.residual_device, _select_tier(capacity))
            rows = plan.index.map_rows(idx)
            if plan.residual_host is None:
                return np.sort(rows)
            return np.sort(self._refine(plan, rows))

    def _union_select(self, plan: UnionScanPlan, auths) -> np.ndarray:
        """Union of per-branch row sets (sorted unique — OR-branch overlaps
        dedup here, ≙ the reference's de-duplication across strategies).
        When every branch is a device-exact scan on one index the whole
        union lowers to a single fused dispatch (the OR dedups in-program)."""
        from geomesa_tpu.index import compiled as _fused
        rows = _fused.try_union_select(self, plan, auths)
        if rows is not None:
            return rows
        sets = [self.select_indices(c, plan=bp, auths=auths)
                for c, bp in plan.branches]
        if not sets:
            return np.empty(0, dtype=np.int64)
        return np.unique(np.concatenate(sets))

    def scan_mask(self, f: Union[str, ir.Filter], auths=None):
        """(plan, device mask over the plan index's sorted rows) — None mask
        when the plan needs host refinement or is candidate-pruned. The mask
        stays on device for aggregation kernels to consume (≙ the shared
        AggregatingScan validate step)."""
        plan = self._apply_auths(self.plan(f), auths)
        if isinstance(plan, UnionScanPlan):
            idx = plan.same_index_device_exact()
            if idx is None or plan.empty:
                return plan, None
            import functools
            masks = [idx.kernels.mask(
                bp2.primary_kind, bp2.boxes_loose, bp2.windows,
                bp2.residual_device)
                for bp2 in (self._apply_auths(bp, auths)
                            for _, bp in plan.branches)]
            return plan, functools.reduce(lambda a, b: a | b, masks)
        if not plan.device_exact:
            return plan, None
        return plan, plan.index.kernels.mask(
            plan.primary_kind, plan.boxes_loose, plan.windows, plan.residual_device)

    def query(self, f: Union[str, ir.Filter], auths=None) -> QueryResult:
        from geomesa_tpu.index.guards import Deadline
        with _trace.trace("query", type=self.sft.name, filter=str(f)):
            dl = Deadline(self.timeout_ms)
            t0 = time.perf_counter()
            plan = self.plan(f)
            plan_ms = (time.perf_counter() - t0) * 1000
            dl.check("plan")
            t1 = time.perf_counter()
            rows = self.select_indices(f, plan=plan, auths=auths)
            dl.check("scan")
            self._write_audit(plan, f, plan_ms,
                              (time.perf_counter() - t1) * 1000, len(rows))
            with _trace.span("serialize", kind="serialize", rows=len(rows)):
                table = self.table.take(rows)
            return QueryResult(rows, table, plan)

    # -- helpers ------------------------------------------------------------

    def _fid_rows(self, f: ir.FidFilter) -> np.ndarray:
        rows = [self.fid_map[fid] for fid in f.fids if fid in self.fid_map]
        return np.array(sorted(rows), dtype=np.int64)

    def _refine(self, plan: IndexScanPlan, rows: np.ndarray) -> np.ndarray:
        """Host f64 re-evaluation of device candidates against the residual
        (≙ the reference's full-filter path over overlapping-range rows).
        Evaluates in place at the candidate rows — no sub-table, and geometry
        predicates run batched (geom_batch) rather than per-feature."""
        if len(rows) == 0 or plan.residual_host is None:
            return rows
        _rdl.check_current("refine")
        with _trace.span("refine", kind="refine", rows=len(rows)):
            mask = self._refine_mask(plan.residual_host, rows)
            return rows[mask]

    def _refine_mask(self, res: ir.Filter, rows: np.ndarray) -> np.ndarray:
        """Residual mask over candidate rows. st_* catalog calls in an AND
        residual route through the device kernels when enabled
        (GEOMESA_TPU_GEOM_KERNELS): the banded classify + exact-f64 refine of
        the uncertain sliver produces the SAME mask as the host oracle, so
        the staged path stays exact while the bulk of the predicate runs
        vmapped on device."""
        from geomesa_tpu import config as _cfg
        parts = res.children if isinstance(res, ir.And) else (res,)
        if _cfg.GEOM_KERNELS.get() \
                and any(isinstance(p, (ir.Func, ir.FuncCmp)) for p in parts):
            from geomesa_tpu.geom.functions import eval_filter_node
            mask = np.ones(len(rows), dtype=bool)
            rest = []
            for p in parts:
                if isinstance(p, (ir.Func, ir.FuncCmp)):
                    mask &= eval_filter_node(p, self.table, rows,
                                             kernels=True)
                else:
                    rest.append(p)
            if rest:
                mask &= _evaluate_at(ir.and_filters(rest), self.table, rows)
            return mask
        return _evaluate_at(res, self.table, rows)


class PreparedQuery:
    """A planned query with constants staged on device.

    ``count_async`` dispatches without blocking (returns the device scalar),
    so many queries pipeline over a single host↔device round trip;
    ``count``/``select_indices`` block for the value. Falls back to the
    planner's general execution when the plan needs host refinement,
    candidate pruning, or fid lookup.
    """

    def __init__(self, planner: QueryPlanner, plan: IndexScanPlan,
                 f: ir.Filter, auths):
        self.planner = planner
        self.plan = plan
        self.filter = f
        self.auths = auths
        self._count_disp = None
        self._fused = None
        if plan.device_exact:
            from geomesa_tpu.index import compiled as _fused
            prog = _fused.prepare_count_program(planner, plan)
            if prog is not None:
                # single-dispatch fused program: cover + scan + residual +
                # count in one device round; constants ride with the call
                self._fused = prog
                self._count_disp = prog.dispatch
                return
            blocks = planner._pruned_blocks(plan)
            if blocks is not None and len(blocks) > 0:
                self._count_disp = plan.index.kernels.prepare_count_blocks(
                    plan.primary_kind, plan.boxes_loose, plan.windows,
                    plan.residual_device, blocks, _prune.BLOCK_SIZE)
            elif blocks is None:
                self._count_disp = plan.index.kernels.prepare_count(
                    plan.primary_kind, plan.boxes_loose, plan.windows,
                    plan.residual_device)
            else:  # provably-empty candidate set
                self._count_disp = lambda: np.zeros((), dtype=np.int32)

    @property
    def device_exact(self) -> bool:
        """True when the whole query resolves on device (no host refine)."""
        return self._count_disp is not None

    def count_async(self):
        """Async dispatch → 0-d device array (None for empty plans)."""
        if self._count_disp is None:
            if self.plan.empty:
                return None
            raise ValueError("plan needs host execution; use count()")
        with _trace.span("device_scan", kind="device_scan"):
            return self._count_disp()

    def count(self) -> int:
        """Blocking count. Audited like planner.count (plan time 0) and
        subject to the planner's cooperative deadline."""
        from geomesa_tpu.index.guards import Deadline
        from geomesa_tpu.index.scan import _fetch
        attrs = {"type": self.planner.sft.name, "prepared": True}
        if _trace.enabled():
            attrs["filter"] = str(self.filter)
        with _trace.trace("count", **attrs):
            dl = Deadline(self.planner.timeout_ms)
            t0 = time.perf_counter()
            if self.plan.empty:
                n = 0
            elif self._count_disp is not None:
                n = int(_fetch(self._count_disp))
            else:
                n = self.planner._count(self.plan, self.filter, self.auths)
            dl.check("scan")
            self.planner._write_audit(self.plan, self.filter, 0.0,
                                      (time.perf_counter() - t0) * 1000, n)
            return n

    def select_indices(self) -> np.ndarray:
        return self.planner.select_indices(self.filter, plan=self.plan,
                                           auths=self.auths)
