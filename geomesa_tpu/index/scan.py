"""Jitted scan kernels: the TPU equivalent of GeoMesa's server-side filters.

≙ the push-down compute contract of SURVEY.md §2.4: ``Z3Filter.inBounds``
(decode z, int box tests — filters/Z3Filter.scala:25-61) plus the residual
CQL evaluation of ``FilterTransformIterator``/``CqlTransformFilter``. Instead
of per-KV decode, the columns are already decoded int32 planes; a scan is one
fused elementwise mask over N rows (bandwidth-bound on HBM), followed by
count / nonzero-compaction / aggregation.

Shape discipline: queries pad their box/window lists to fixed sizes (powers of
two) so XLA compiles one kernel per (primary_kind, n_boxes, n_windows,
residual_structure) — constants ride in arrays, so new query *values* never
recompile.

Exactness contract (mirrors the reference's contained-vs-overlapping ranges +
useFullFilter, Z3IndexKeySpace.scala:235-249):
  - ``strict`` masks use cell-interior bounds → every hit is a definite match
    (like rows in a *contained* range: no further filtering)
  - ``loose`` masks use cell-covering bounds → superset of matches; rows in
    loose∖strict are the boundary band the host refines in f64
"""

from __future__ import annotations

import functools
from typing import Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from geomesa_tpu import trace as _trace
from geomesa_tpu.filter import ir
from geomesa_tpu.obs import attrib as _attrib
from geomesa_tpu.obs import profiling as _prof


class _RoundLedger:
    """Process-wide host↔device round counter: every kernel dispatch and
    every constant upload is one potential tunnel round trip (each pays the
    ``dispatch_floor_ms_per_query`` the bench tracks). ``rounds_since`` a
    snapshot is how the cfg14 bench and the fused-query tests pin
    ``dispatches_per_cold_query`` — the fused path must read exactly 1."""

    __slots__ = ("dispatches", "uploads")

    def __init__(self):
        self.dispatches = 0
        self.uploads = 0

    def snapshot(self):
        return (self.dispatches, self.uploads)

    def rounds_since(self, snap) -> int:
        return (self.dispatches - snap[0]) + (self.uploads - snap[1])


ROUNDS = _RoundLedger()


def _fetch(dispatch, *args):
    """Run a kernel dispatch under a ``device_scan`` span (host-side enqueue)
    and block under a ``device_wait`` span — separating the dispatch floor
    from true device time in every trace. Returns the ready device value.
    Variadic so hot paths pass ``(fn, *args)`` without a closure alloc."""
    ROUNDS.dispatches += 1
    return _trace.device_fetch(jax.block_until_ready, dispatch, *args)

# -- primary spatial/temporal masks -----------------------------------------


def _ge62(hi, lo, qhi, qlo):
    """Lexicographic fixed-point (hi, lo) >= (qhi, qlo)."""
    return (hi > qhi) | ((hi == qhi) & (lo >= qlo))


def _le62(hi, lo, qhi, qlo):
    return (hi < qhi) | ((hi == qhi) & (lo <= qlo))


def _point_box_pairwise(cols, boxes: jnp.ndarray) -> jnp.ndarray:
    """(N, B) per-box containment matrix for point layers — EXACT (fp62
    planes). boxes (B, 8) int32: [qxlo_hi, qxlo_lo, qxhi_hi, qxhi_lo,
    qylo_hi, qylo_lo, qyhi_hi, qyhi_lo]. Empty boxes use qlo=max/qhi=0 so
    nothing matches."""
    xi, xl = cols["xi"][:, None], cols["xl"][:, None]
    yi, yl = cols["yi"][:, None], cols["yl"][:, None]
    b = boxes[None, :, :]
    return (
        _ge62(xi, xl, b[..., 0], b[..., 1]) & _le62(xi, xl, b[..., 2], b[..., 3])
        & _ge62(yi, yl, b[..., 4], b[..., 5]) & _le62(yi, yl, b[..., 6], b[..., 7])
    )


def _point_box_mask(cols, boxes: jnp.ndarray) -> jnp.ndarray:
    """Any-box containment for point layers — EXACT (fp62 planes)."""
    return jnp.any(_point_box_pairwise(cols, boxes), axis=1)


def _bbox_overlap_pairwise(cols, boxes: jnp.ndarray) -> jnp.ndarray:
    """(N, B) per-box envelope-overlap matrix for extent layers — EXACT on
    envelopes (geometry-level refinement is the spatial residual's job)."""
    b = boxes[None, :, :]
    return (
        _le62(cols["bxmin_i"][:, None], cols["bxmin_l"][:, None], b[..., 2], b[..., 3])
        & _ge62(cols["bxmax_i"][:, None], cols["bxmax_l"][:, None], b[..., 0], b[..., 1])
        & _le62(cols["bymin_i"][:, None], cols["bymin_l"][:, None], b[..., 6], b[..., 7])
        & _ge62(cols["bymax_i"][:, None], cols["bymax_l"][:, None], b[..., 4], b[..., 5])
    )


def _bbox_overlap_mask(cols, boxes: jnp.ndarray) -> jnp.ndarray:
    """Any-box envelope-overlap for extent layers."""
    return jnp.any(_bbox_overlap_pairwise(cols, boxes), axis=1)


def _time_mask(cols, windows: jnp.ndarray) -> jnp.ndarray:
    """Any-window (bin, off) containment (≙ Z3Filter.timeInBounds semantics,
    exact: offsets are unnormalized period units). windows (T,4) int32
    [bin_lo, off_lo, bin_hi, off_hi]; empty windows bin_lo>bin_hi."""
    b = cols["bin"][:, None]
    o = cols["off"][:, None]
    blo, olo = windows[None, :, 0], windows[None, :, 1]
    bhi, ohi = windows[None, :, 2], windows[None, :, 3]
    after_lo = (b > blo) | ((b == blo) & (o >= olo))
    before_hi = (b < bhi) | ((b == bhi) & (o <= ohi))
    return jnp.any(after_lo & before_hi & (blo <= bhi), axis=1)


PRIMARY_FNS: Dict[str, Callable] = {
    "point_boxes": _point_box_mask,
    "bbox_overlap": _bbox_overlap_mask,
}

# device columns each primary mask reads (batch kernels pre-touch these
# before entering a mapped body — see count_multi_blocks)
_PRIMARY_COLS: Dict[str, tuple] = {
    "point_boxes": ("xi", "xl", "yi", "yl"),
    "bbox_overlap": ("bxmin_i", "bxmin_l", "bxmax_i", "bxmax_l",
                     "bymin_i", "bymin_l", "bymax_i", "bymax_l"),
}


# -- certified f32 geometry predicates ---------------------------------------
#
# The fp62 planes make BOX predicates exact on device; SEGMENT predicates
# (exact intersects for extent features) use f32 with a computed CERTAINTY
# BAND instead: every orientation sign carries an error bound covering both
# the f32 arithmetic and the f64→f32 input rounding, so each feature
# classifies as certain-hit / certain-miss / uncertain — and only the
# uncertain sliver (rows within ~1e-5 deg of a boundary) goes to the host's
# exact f64 refine. This is the strict/loose band discipline applied to
# JTS-style predicates.

_F32_EPS = np.float32(1.2e-7)     # 2^-23 with margin
_IN_DELTA = np.float32(2.5e-5)    # |f64 coord - f32 coord| bound (lon/lat)
_DY_BAND = np.float32(3e-5)       # vertex y-tie band for the crossing rule


def _orient_band(px, py, qx, qy, rx, ry):
    """Signed area orientation of (p,q,r) with a conservative error bound."""
    d1x = qx - px
    d1y = qy - py
    d2x = rx - px
    d2y = ry - py
    t1 = d1x * d2y
    t2 = d1y * d2x
    det = t1 - t2
    tol = (8 * _F32_EPS * (jnp.abs(t1) + jnp.abs(t2))
           + 4 * _IN_DELTA * (jnp.abs(d1x) + jnp.abs(d1y)
                              + jnp.abs(d2x) + jnp.abs(d2y)))
    return det, tol


def _pip_band(px, py, ex1, ey1, ex2, ey2, evalid=None):
    """(certainly-inside, certainly-outside) of points vs polygon edges via
    the half-open crossing rule; uncertain when any edge's crossing decision
    sits inside its error band or a vertex y ties the ray. ``evalid``
    masks padded edges out of both crossings and uncertainty (pair-kernel
    padded tables)."""
    cond = (ey1 > py) != (ey2 > py)
    o, t = _orient_band(ex1, ey1, ex2, ey2, px, py)
    upward = ey2 > ey1
    cross = cond & jnp.where(upward, o > t, o < -t)
    unc = (cond & (jnp.abs(o) <= t)) \
        | (jnp.abs(ey1 - py) <= _DY_BAND) | (jnp.abs(ey2 - py) <= _DY_BAND)
    if evalid is not None:
        cross = cross & evalid
        unc = unc & evalid
    inside = (jnp.sum(cross, axis=-1) % 2) == 1
    any_unc = jnp.any(unc, axis=-1)
    return inside & ~any_unc, ~inside & ~any_unc


def _segpair_band(ax, ay, bx, by, cx, cy, dx, dy):
    """(certain-intersect, certain-miss) for segment (a,b) vs edge (c,d)."""
    o1, t1 = _orient_band(ax, ay, bx, by, cx, cy)
    o2, t2 = _orient_band(ax, ay, bx, by, dx, dy)
    o3, t3 = _orient_band(cx, cy, dx, dy, ax, ay)
    o4, t4 = _orient_band(cx, cy, dx, dy, bx, by)
    opp12 = ((o1 > t1) & (o2 < -t2)) | ((o1 < -t1) & (o2 > t2))
    opp34 = ((o3 > t3) & (o4 < -t4)) | ((o3 < -t3) & (o4 > t4))
    same12 = ((o1 > t1) & (o2 > t2)) | ((o1 < -t1) & (o2 < -t2))
    same34 = ((o3 > t3) & (o4 > t4)) | ((o3 < -t3) & (o4 < -t4))
    return opp12 & opp34, same12 | same34


_EARTH_R_M = 6371008.8


def _haversine_f32(lon, lat, qlon, qlat):
    """Great-circle distance in meters, f32 (matches process/geo.haversine_m
    up to f32 rounding — callers that need exact ranks re-check in f64)."""
    rad = jnp.float32(np.pi / 180.0)
    la1 = lat * rad
    la2 = qlat * rad
    dla = (qlat - lat) * rad
    dlo = (qlon - lon) * rad
    a = jnp.sin(dla / 2) ** 2 + jnp.cos(la1) * jnp.cos(la2) * jnp.sin(dlo / 2) ** 2
    return jnp.float32(2 * _EARTH_R_M) * jnp.arcsin(jnp.sqrt(jnp.clip(a, 0.0, 1.0)))


# -- residual predicate compiler --------------------------------------------


class Unsupported(Exception):
    """Raised when a predicate subtree can't run on device."""


# attr type names whose device columns are exact representations
_EXACT_DEVICE_TYPES = {"Int", "Integer", "Boolean", "String", "Float"}


def compile_residual(f: Optional[ir.Filter], sft, string_vocabs: Dict[str, list],
                     available: Optional[set] = None):
    """IR → (structure_key, params ndarray list, fn(cols, params) -> mask).

    Raises Unsupported for subtrees that must stay host-side — including
    predicates on attributes OUTSIDE the device column projection
    (``available``, when given: the column-group narrow-scan contract).
    Constants are hoisted into the params list so differing query values
    share one compiled kernel (structure_key captures only the tree shape).
    """
    if f is None:
        return "none", [], None

    def check_available(attr: str) -> None:
        if available is not None and attr not in available:
            raise Unsupported(f"{attr} not in the device column group")

    params: list = []

    def const(v, dtype) -> int:
        params.append(np.asarray(v, dtype=dtype))
        return len(params) - 1

    def walk(node: ir.Filter) -> Tuple[str, Callable]:
        if isinstance(node, ir.Include):
            return "inc", lambda cols, p: jnp.ones(
                next(iter(cols.values())).shape[0], dtype=bool)
        if isinstance(node, ir.Exclude):
            return "exc", lambda cols, p: jnp.zeros(
                next(iter(cols.values())).shape[0], dtype=bool)
        if isinstance(node, ir.And):
            keys, fns = zip(*(walk(c) for c in node.children))
            return "and(" + ",".join(keys) + ")", \
                lambda cols, p, fns=fns: functools.reduce(
                    jnp.logical_and, [g(cols, p) for g in fns])
        if isinstance(node, ir.Or):
            keys, fns = zip(*(walk(c) for c in node.children))
            return "or(" + ",".join(keys) + ")", \
                lambda cols, p, fns=fns: functools.reduce(
                    jnp.logical_or, [g(cols, p) for g in fns])
        if isinstance(node, ir.Not):
            k, g = walk(node.child)
            return f"not({k})", lambda cols, p, g=g: ~g(cols, p)
        if isinstance(node, ir.Cmp):
            check_available(node.attr)
            attr = sft.attribute(node.attr)
            if attr.type_name == "String":
                if node.op not in ("=", "<>"):
                    raise Unsupported("ordered string cmp on device")
                vocab = string_vocabs.get(node.attr)
                if vocab is None:
                    raise Unsupported("no vocab")
                try:
                    code = vocab.index(node.value)
                except ValueError:
                    code = -1  # matches nothing
                i = const(code, np.int32)
                if node.op == "=":
                    return f"seq:{node.attr}", lambda cols, p, i=i, a=node.attr: cols[a] == p[i]
                return f"sne:{node.attr}", lambda cols, p, i=i, a=node.attr: cols[a] != p[i]
            if attr.type_name not in _EXACT_DEVICE_TYPES:
                raise Unsupported(f"{attr.type_name} cmp is inexact on device")
            dtype = np.float32 if attr.type_name == "Float" else np.int32
            i = const(node.value, dtype)
            op = node.op
            key = f"cmp{op}:{node.attr}"

            def g(cols, p, i=i, a=node.attr, op=op):
                c = cols[a]
                v = p[i]
                return {"=": c == v, "<>": c != v, "<": c < v,
                        "<=": c <= v, ">": c > v, ">=": c >= v}[op]
            return key, g
        if isinstance(node, ir.In):
            check_available(node.attr)
            attr = sft.attribute(node.attr)
            if attr.type_name == "String":
                vocab = string_vocabs.get(node.attr)
                if vocab is None:
                    raise Unsupported("no vocab")
                codes = [vocab.index(v) for v in node.values if v in vocab] or [-1]
            elif attr.type_name in ("Int", "Integer"):
                codes = [int(v) for v in node.values]
            else:
                raise Unsupported("IN on non-int/string")
            # pad to pow2 so membership lists of similar size share kernels
            size = max(1, 1 << (len(codes) - 1).bit_length())
            padded = codes + [codes[-1]] * (size - len(codes))
            i = const(padded, np.int32)
            return f"in{size}:{node.attr}", \
                lambda cols, p, i=i, a=node.attr: jnp.any(
                    cols[a][:, None] == p[i][None, :], axis=1)
        if isinstance(node, ir.During):
            dtg = sft.dtg_attribute
            if dtg is None or node.attr != dtg.name:
                raise Unsupported("During on non-dtg attr")
            # exact (bin, off) bounds computed host-side in the planner via
            # params: [bin_lo, off_lo, bin_hi, off_hi] — see plan_residual
            raise Unsupported("During handled by primary time windows")
        raise Unsupported(type(node).__name__)

    key, fn = walk(f)
    return key, params, fn


def split_residual(f: Optional[ir.Filter], sft, string_vocabs,
                   available: Optional[set] = None):
    """Split a residual filter into (device_part, host_part).

    AND trees split per-child; any child the device compiler rejects stays on
    the host (≙ reference splitting between pushed-down filter and client
    post-filter) — including predicates on attributes outside the device
    column group. Returns (device_ir_or_None, host_ir_or_None).
    """
    if f is None or isinstance(f, ir.Include):
        return None, None
    children = f.children if isinstance(f, ir.And) else (f,)
    dev, host = [], []
    for c in children:
        try:
            compile_residual(c, sft, string_vocabs, available)
            dev.append(c)
        except Unsupported:
            host.append(c)
    return (
        ir.and_filters(dev) if dev else None,
        ir.and_filters(host) if host else None,
    )


# -- fused scan entry points ------------------------------------------------


@functools.lru_cache(maxsize=256)
def _mask_kernel(primary_kind: str, has_time: bool, residual_key: str, n_boxes: int, n_windows: int):
    """Build the fused mask fn for one structural signature."""

    def mask(cols, boxes, windows, rparams, residual_fn):
        m = None
        if primary_kind != "none":
            m = PRIMARY_FNS[primary_kind](cols, boxes)
        if has_time:
            tm = _time_mask(cols, windows)
            m = tm if m is None else (m & tm)
        if residual_fn is not None:
            rm = residual_fn(cols, rparams)
            m = rm if m is None else (m & rm)
        if m is None:
            n = next(iter(cols.values())).shape[0]
            m = jnp.ones(n, dtype=bool)
        if "__valid__" in cols:
            m = m & cols["__valid__"]
        return m

    return mask


def _grid_scatter(xs, ys, mask, weight, grid, width: int, height: int):
    """Masked scatter-add onto a (height, width) raster. grid =
    [xmin, ymin, xmax, ymax] f32 (GridSnap.scala:23 snap semantics)."""
    xmin, ymin, xmax, ymax = grid[0], grid[1], grid[2], grid[3]
    fx = (xs - xmin) / (xmax - xmin)
    fy = (ys - ymin) / (ymax - ymin)
    inb = mask & (fx >= 0) & (fx < 1) & (fy >= 0) & (fy < 1)
    ix = jnp.clip((fx * width).astype(jnp.int32), 0, width - 1)
    iy = jnp.clip((fy * height).astype(jnp.int32), 0, height - 1)
    w = jnp.where(inb, weight if weight is not None else 1.0, 0.0).astype(jnp.float32)
    return jnp.zeros((height, width), dtype=jnp.float32).at[iy, ix].add(w)


class _LazyBlockGather:
    """Dict-like view reading candidate blocks of a column on first access,
    so a pruned scan touches only the columns its mask needs.

    Reads are vmapped ``dynamic_slice``s — nb contiguous block_size-row
    slices — which XLA lowers to an efficient slice-gather (one HBM burst per
    block). An elementwise ``col[flat_idx]`` gather here lowers to per-row
    accesses and measured ~75x slower on TPU."""

    def __init__(self, cols: Dict[str, jnp.ndarray], starts: jnp.ndarray,
                 block_size: int, total: int):
        self._cols = cols
        self._starts = starts          # (nb,) clipped int32 row starts
        self._bsz = block_size
        self._total = total            # nb * block_size
        self._cache: Dict[str, jnp.ndarray] = {}

    def __getitem__(self, k: str) -> jnp.ndarray:
        if k not in self._cache:
            from jax import lax, vmap
            v = self._cols[k]
            bsz = self._bsz
            sl = vmap(lambda s: lax.dynamic_slice(v, (s,), (bsz,)))(self._starts)
            self._cache[k] = sl.reshape(self._total)
        return self._cache[k]

    def __contains__(self, k: str) -> bool:
        return k in self._cols

    def values(self):
        # row-count probes (Include/Exclude) only need a .shape[0]
        yield self._starts.repeat(self._bsz)


_TRANSFER_SHAPES_WARMED = False
# batch tiers already pre-touched — warm_transfer_shapes(batch_sizes=...)
# extends this set for the scheduler's flush sizes
_WARMED_BATCH_SIZES: set = set()


def warm_transfer_shapes(batch_sizes=(), fused_indexes=()) -> None:
    """Pre-touch the small host→device transfer shapes queries use.

    Through the axon RPC tunnel the FIRST device_put of each new array shape
    blocks ~140ms (per-shape channel setup); afterwards the same shape
    transfers in sub-ms. Warming the power-of-two box/window/param shapes at
    index-build time moves that cost out of the cold-query path (the r2 bench
    showed plan+stage at 265ms — all of it was two cold transfer shapes).

    ``batch_sizes``: extra coalesced-batch tiers to warm (boxes/windows/
    params at each size) — the micro-batching scheduler passes its flush
    tiers at construction so the FIRST fused dispatch doesn't eat the
    per-shape transfer cliff. Each size rounds up to the next power of two
    (the pad the dispatch path actually ships) and warms at most once.

    ``fused_indexes``: indexes whose single-dispatch fused program tiers
    (index/compiled.py) should compile + run once now instead of on the
    first cold query. The fused packed-constant vector is a pow2 1-D int32
    — a shape this function already warms — so program warming here is
    about the XLA compile, not a new transfer shape."""
    global _TRANSFER_SHAPES_WARMED
    import jax
    puts = []
    if not _TRANSFER_SHAPES_WARMED:
        _TRANSFER_SHAPES_WARMED = True
        for b in (1, 2, 4, 8, 16):
            puts.append(jax.device_put(np.zeros((b, 8), np.int32)))  # boxes
            puts.append(jax.device_put(np.zeros((b, 4), np.int32)))  # windows
            puts.append(jax.device_put(np.zeros((b,), np.int32)))    # params
            _WARMED_BATCH_SIZES.add(b)
        for b in (32, 64):
            puts.append(jax.device_put(np.zeros((b, 8), np.int32)))  # batch boxes
        # padded block-id vectors (_pad_blocks pow2 tiers): a cold query's
        # candidate-block upload was the r4 plan-stage cost (131ms measured —
        # one per-shape channel setup through the tunnel)
        for nb in (32, 64, 128, 256, 512, 1024, 2048, 4096, 8192,
                   16384, 32768, 65536):
            puts.append(jax.device_put(np.zeros((nb,), np.int32)))
        puts.append(jax.device_put(np.zeros((), np.int32)))
        puts.append(jax.device_put(np.zeros((), np.float32)))
    for b in batch_sizes:
        b = max(1, 1 << max(0, (int(b) - 1)).bit_length())
        if b in _WARMED_BATCH_SIZES:
            continue
        _WARMED_BATCH_SIZES.add(b)
        puts.append(jax.device_put(np.zeros((b, 8), np.int32)))      # boxes
        puts.append(jax.device_put(np.zeros((b, 4), np.int32)))      # windows
        puts.append(jax.device_put(np.zeros((b,), np.int32)))        # params
    if puts:
        jax.block_until_ready(puts)
    for idx in fused_indexes:
        try:
            from geomesa_tpu.index import compiled as _fused
            _fused.warm_programs(idx)
        except Exception:
            pass   # warming is best-effort; the query path compiles lazily


import weakref

# live ScanKernels instances (weak: a dropped index frees its kernels)
_KERNEL_INSTANCES: "weakref.WeakSet" = weakref.WeakSet()


def _register_kernel_gauge() -> None:
    """`kernels.compiled` gauge: compiled scan kernels resident across every
    live ScanKernels instance (the quantity the per-instance LRU bounds)."""
    global _KERNEL_GAUGE_REGISTERED
    if _KERNEL_GAUGE_REGISTERED:
        return
    _KERNEL_GAUGE_REGISTERED = True
    from geomesa_tpu.metrics import REGISTRY
    REGISTRY.set_gauge(
        "kernels.compiled",
        lambda: sum(len(k._jitted) for k in list(_KERNEL_INSTANCES)))


_KERNEL_GAUGE_REGISTERED = False


class ModuleKernelCache:
    """Bounded LRU for module-level jitted kernels (sort / gather / merge).

    The build-path jits in ``index/spatial.py`` used to live in module
    globals keyed by nothing — one padded-shape compile pinned forever, and
    a long-running ingester visiting many pow2 tiers accumulated them all.
    Routing them through this cache bounds residency by
    ``GEOMESA_TPU_KERNEL_CACHE`` (shape-keyed entries, LRU eviction) and —
    because instances register in ``_KERNEL_INSTANCES`` exactly like
    ``ScanKernels`` — counts them in the ``kernels.compiled`` gauge and the
    recompile detector."""

    def __init__(self, kernel_id: str):
        self.kernel_id = kernel_id
        from collections import OrderedDict
        self._jitted: "OrderedDict[tuple, Callable]" = OrderedDict()
        self._sig_seen: Dict[str, set] = {}
        _KERNEL_INSTANCES.add(self)
        _register_kernel_gauge()

    def get(self, key: tuple, builder):
        """Return the cached kernel for ``key`` or build+insert it.

        ``builder`` is a zero-arg callable returning the jitted fn; it runs
        only on a miss. Eviction drops the least-recently-used shape — an
        evicted shape simply recompiles on next use."""
        hit = self._jitted.get(key)
        if hit is not None:
            self._jitted.move_to_end(key)
            return hit
        jitted = builder()
        if _prof.enabled():
            _prof.note_signature(self._sig_seen, self.kernel_id, key)
        self._jitted[key] = jitted
        from geomesa_tpu import config
        lru_cap = max(1, config.KERNEL_CACHE.get())
        while len(self._jitted) > lru_cap:
            self._jitted.popitem(last=False)
        return jitted


class ScanKernels:
    """Compiled-scan cache for one DeviceTable (one index).

    ``_jitted`` is a small LRU (``GEOMESA_TPU_KERNEL_CACHE`` signatures):
    long-lived servers seeing many residual structures stay bounded instead
    of accumulating compiled kernels forever; an evicted signature simply
    recompiles on next use (prepared dispatchers hold their own reference,
    so in-flight handles never lose their kernel)."""

    def __init__(self, device_cols: Dict[str, jnp.ndarray]):
        self.cols = device_cols
        from collections import OrderedDict
        self._jitted: "OrderedDict[tuple, Callable]" = OrderedDict()
        # kernel_id -> signature hashes already compiled by THIS instance:
        # the recompile detector's memory (obs/profiling.note_signature) —
        # per-instance so two indexes compiling their own kernels never
        # read as shape churn
        self._sig_seen: Dict[str, set] = {}
        _KERNEL_INSTANCES.add(self)
        _register_kernel_gauge()
        warm_transfer_shapes()

    def _get(self, mode: str, primary_kind: str, has_time: bool,
             residual_key: str, residual_fn, n_boxes: int, n_windows: int,
             capacity: int = 0):
        key = (mode, primary_kind, has_time, residual_key, n_boxes, n_windows, capacity)
        hit = self._jitted.get(key)
        if hit is not None:
            self._jitted.move_to_end(key)
            return hit
        mask_fn = _mask_kernel(primary_kind, has_time, residual_key, n_boxes, n_windows)

        if mode == "count":
            def run(cols, boxes, windows, rparams):
                return jnp.sum(mask_fn(cols, boxes, windows, rparams, residual_fn))
        elif mode == "mask":
            def run(cols, boxes, windows, rparams):
                return mask_fn(cols, boxes, windows, rparams, residual_fn)
        elif mode == "count_at":
            # candidate-pruned scan (attribute index): gather the candidate
            # rows' columns, mask only those (≙ scanning one key range
            # instead of the table)
            def run(cols, boxes, windows, rparams, idxs, nvalid):
                g = {k: v[idxs] for k, v in cols.items()}
                m = mask_fn(g, boxes, windows, rparams, residual_fn)
                m = m & (jnp.arange(idxs.shape[0]) < nvalid)
                return jnp.sum(m)
        elif mode == "select_at":
            def run(cols, boxes, windows, rparams, idxs, nvalid):
                g = {k: v[idxs] for k, v in cols.items()}
                m = mask_fn(g, boxes, windows, rparams, residual_fn)
                m = m & (jnp.arange(idxs.shape[0]) < nvalid)
                sel = jnp.nonzero(m, size=idxs.shape[0], fill_value=idxs.shape[0])[0]
                return jnp.concatenate([
                    jnp.sum(m)[None].astype(jnp.int32), sel.astype(jnp.int32)])
        elif mode == "count_multi":
            # per-box counts in ONE kernel: the non-box constraints evaluate
            # once, then lax.map runs one fused box-count pass per box (B
            # sequential bandwidth-bound scans — no (N, B) materialization).
            # The expanding-radius KNN schedule rides this: every radius
            # costs one extra scan, the whole schedule one round trip.
            from jax import lax

            def run(cols, boxes, windows, rparams):
                base = None
                if has_time:
                    base = _time_mask(cols, windows)
                if residual_fn is not None:
                    rm = residual_fn(cols, rparams)
                    base = rm if base is None else (base & rm)
                if "__valid__" in cols:
                    v = cols["__valid__"]
                    base = v if base is None else (base & v)

                def one(b):
                    m = PRIMARY_FNS[primary_kind](cols, b[None, :])
                    return jnp.sum(m if base is None else (m & base))

                return lax.map(one, boxes)
        elif mode == "density_compact":
            # heat-map over a full-table mask: compact matching rows first
            # (nonzero + gather), THEN scatter-add — a TPU scatter prices per
            # update, so scattering 100M mostly-zero weights (the r3 design)
            # cost ~1s where compact-then-scatter costs ~1ms. Returns
            # (grid, true_count); the caller sizes `cap` from a count so
            # overflow cannot occur on static data.
            cap, width, height, wname = capacity
            n = next(iter(self.cols.values())).shape[0]

            def run(cols, boxes, windows, rparams, grid):
                m = mask_fn(cols, boxes, windows, rparams, residual_fn)
                sel = jnp.nonzero(m, size=cap, fill_value=n)[0]
                ok = sel < n
                seli = jnp.clip(sel, 0, n - 1)
                xs = cols["xf"][seli]
                ys = cols["yf"][seli]
                w = cols[wname][seli].astype(jnp.float32) if wname else None
                out = _grid_scatter(xs, ys, ok, w, grid, width, height)
                return out, jnp.sum(m)
        elif mode in ("count_blocks", "count_multi_blocks", "select_blocks",
                      "density_blocks", "topk_blocks",
                      "intersects_band_blocks"):
            # range-pruned gather scan: block ids (pad = -1) expand to row
            # indices with an iota, candidate rows gather from HBM, and the
            # FULL exact mask re-applies — so the host cover only needs to be
            # a superset (≙ scanning the reference's ≤2000 key ranges instead
            # of the table; block granularity plays the tablet-range role).
            n = next(iter(self.cols.values())).shape[0]
            nblk, bsz, sel_cap = capacity[:3]

            def expand_blocks(cols, block_ids):
                """block ids → (valid membership mask, row ids, lazy gather).
                dynamic_slice clamps out-of-range starts, so the last
                partial block re-reads a suffix of the previous one; the
                membership test (row belongs to ITS intended block) masks
                those re-reads and the -1 pad blocks without double counts.
                Single home for this logic — every block mode goes through it."""
                starts = block_ids * bsz
                astart = jnp.clip(starts, 0, max(0, n - bsz))
                rows = (astart[:, None]
                        + jnp.arange(bsz, dtype=jnp.int32)[None, :])
                valid = ((block_ids >= 0)[:, None]
                         & (rows >= starts[:, None])
                         & (rows < starts[:, None] + bsz)).reshape(-1)
                g = _LazyBlockGather(cols, astart, bsz, astart.shape[0] * bsz)
                return valid, rows.reshape(-1), g

            def blocks_mask(cols, boxes, windows, rparams, block_ids):
                valid, rows, g = expand_blocks(cols, block_ids)
                m = mask_fn(g, boxes, windows, rparams, residual_fn) & valid
                return m, rows, g

            if mode == "count_blocks":
                def run(cols, boxes, windows, rparams, block_ids):
                    m, _, _ = blocks_mask(cols, boxes, windows, rparams, block_ids)
                    return jnp.sum(m)
            elif mode == "count_multi_blocks":
                # batched serving: B independent box-queries against the
                # UNION of their candidate blocks in one dispatch — the
                # gather happens once, then each box is a cheap mask over
                # the resident candidates. Per-query cost collapses to
                # microseconds (the per-dispatch RPC overhead amortizes
                # across the whole batch). The per-box scans run through
                # lax.map with a small vmapped batch_size: loop machinery
                # costs ~0.4ms/iteration on the CPU backend (a fixed ~28ms
                # floor for a 64-query batch regardless of scan size), so
                # chunking 8 boxes per iteration cuts that 8x while keeping
                # the materialized pairwise mask bounded to 8 columns (the
                # full (rows, B) matrix measured SLOWER — broadcast
                # intermediates blow the cache).
                def run(cols, boxes, windows, rparams, block_ids):
                    valid, _, g = expand_blocks(cols, block_ids)
                    base = valid
                    if has_time:
                        base = base & _time_mask(g, windows)
                    if residual_fn is not None:
                        base = base & residual_fn(g, rparams)
                    if "__valid__" in g:
                        base = base & g["__valid__"]
                    # materialize the primary's columns OUTSIDE the mapped
                    # body: the lazy gather caches per column, and a first
                    # touch inside the scan would leak a traced value
                    for k in _PRIMARY_COLS[primary_kind]:
                        g[k]

                    def one(b):
                        return jnp.sum(
                            PRIMARY_FNS[primary_kind](g, b[None, :]) & base)

                    from jax import lax
                    return lax.map(one, boxes,
                                   batch_size=min(8, boxes.shape[0]))
            elif mode == "topk_blocks":
                # pruned KNN: top_k over gathered candidate blocks only.
                # lax.top_k lowers to a full sort of its operand on TPU, so
                # shrinking the operand from N rows to nb*block_size is the
                # entire win (~N/(nb*B) factor); the host drives the radius
                # bound so the candidate set provably contains the true k
                # nearest (guarantee re-check in process/knn.py).
                m_cap = capacity[3]

                def run(cols, boxes, windows, rparams, q, block_ids):
                    m, rowids, g = blocks_mask(cols, boxes, windows, rparams,
                                               block_ids)
                    d = _haversine_f32(g["xf"], g["yf"], q[0], q[1])
                    d = jnp.where(m, d, jnp.inf)
                    vals, idxs = jax.lax.top_k(-d, m_cap)
                    sel = rowids[jnp.clip(idxs, 0, rowids.shape[0] - 1)]
                    return -vals, sel.astype(jnp.int32)
            elif mode == "intersects_band_blocks":
                # exact segment-vs-polygon intersects over candidate blocks,
                # in f32 with certainty bands: returns [certain_hit_count,
                # n_uncertain, uncertain_row_ids...]; the host refines only
                # the uncertain sliver in exact f64 (geom_batch)
                unc_cap = capacity[3]

                def run(cols, boxes, windows, rparams, edges, block_ids):
                    m, rowids, g = blocks_mask(cols, boxes, windows, rparams,
                                               block_ids)
                    ax, ay = g["sx1"], g["sy1"]
                    bx, by = g["sx2"], g["sy2"]
                    ex1 = edges[None, :, 0]
                    ey1 = edges[None, :, 1]
                    ex2 = edges[None, :, 2]
                    ey2 = edges[None, :, 3]
                    hit_p, miss_p = _segpair_band(
                        ax[:, None], ay[:, None], bx[:, None], by[:, None],
                        ex1, ey1, ex2, ey2)
                    in_a, out_a = _pip_band(ax[:, None], ay[:, None],
                                            ex1, ey1, ex2, ey2)
                    in_b, out_b = _pip_band(bx[:, None], by[:, None],
                                            ex1, ey1, ex2, ey2)
                    hit = m & (in_a | in_b | jnp.any(hit_p, axis=1))
                    miss = out_a & out_b & jnp.all(miss_p, axis=1)
                    unc = m & ~hit & ~miss
                    total = m.shape[0]
                    sel = jnp.nonzero(unc, size=unc_cap, fill_value=total)[0]
                    rows = jnp.where(sel < total,
                                     rowids[jnp.clip(sel, 0, total - 1)], n)
                    return jnp.concatenate([
                        jnp.sum(hit)[None].astype(jnp.int32),
                        jnp.sum(unc)[None].astype(jnp.int32),
                        rows.astype(jnp.int32)])
            elif mode == "density_blocks":
                # pruned heat-map: candidate blocks gather (contiguous HBM
                # bursts) + masked scatter of only nb*block_size rows
                width, height, wname = capacity[3:]

                def run(cols, boxes, windows, rparams, grid, block_ids):
                    m, _, g = blocks_mask(cols, boxes, windows, rparams, block_ids)
                    w = g[wname].astype(jnp.float32) if wname else None
                    out = _grid_scatter(g["xf"], g["yf"], m, w, grid,
                                        width, height)
                    return out, jnp.sum(m)
            else:
                def run(cols, boxes, windows, rparams, block_ids):
                    m, rowids, _ = blocks_mask(cols, boxes, windows, rparams, block_ids)
                    total = m.shape[0]
                    sel = jnp.nonzero(m, size=sel_cap, fill_value=total)[0]
                    rows = jnp.where(sel < total,
                                     rowids[jnp.clip(sel, 0, total - 1)], n)
                    return jnp.concatenate([
                        jnp.sum(m)[None].astype(jnp.int32),
                        rows.astype(jnp.int32)])
        elif mode == "topk":
            # device KNN: haversine distance + lax.top_k as ONE fused
            # reduction over the table (the reference's expanding-radius
            # iteration — KNearestNeighborSearchProcess — exists because
            # storage scans price by range; a TPU prices by full-array
            # reductions, so the whole search is a single kernel + one small
            # readback). Distances are f32; callers re-rank the top-m margin
            # exactly on host (m >= 2k makes f32 rank noise harmless).
            m_cap = capacity

            def run(cols, boxes, windows, rparams, q):
                m = mask_fn(cols, boxes, windows, rparams, residual_fn)
                d = _haversine_f32(cols["xf"], cols["yf"], q[0], q[1])
                d = jnp.where(m, d, jnp.inf)
                vals, idxs = jax.lax.top_k(-d, m_cap)
                return -vals, idxs.astype(jnp.int32)
        elif mode == "select_packed":
            # single-roundtrip select: [count, idx...] in ONE int32 array so
            # the host pays a single device-fetch latency (transfers/dispatch
            # are async; only result syncs block — this matters enormously
            # when the chip sits behind an RPC tunnel).
            n = next(iter(self.cols.values())).shape[0]

            def run(cols, boxes, windows, rparams):
                m = mask_fn(cols, boxes, windows, rparams, residual_fn)
                idx = jnp.nonzero(m, size=capacity, fill_value=n)[0]
                return jnp.concatenate([
                    jnp.sum(m)[None].astype(jnp.int32), idx.astype(jnp.int32)])
        else:
            raise ValueError(mode)

        jitted = jax.jit(run)
        kid = f"{mode}.{primary_kind}"
        if _prof.enabled():
            # recompile detection: a second distinct signature for this
            # kernel id (or a re-jit of an evicted one) is shape churn —
            # counted + flight-evented with the triggering shape. The
            # probe then times the first invocation's XLA compile and
            # captures the kernel's cost analysis (flops/bytes gauges).
            _prof.note_signature(self._sig_seen, kid, key, shape={
                "mode": mode, "primary": primary_kind,
                "residual": residual_key, "n_boxes": n_boxes,
                "n_windows": n_windows, "capacity": repr(capacity)})
            jitted = _prof.kernel_probe(jitted, kid, n_boxes)
        elif _attrib.enabled():
            # per-(kernel, tier) compile attribution: the first invocation
            # is where XLA traces + compiles, and that cost lands on the
            # kernel's labeled series instead of vanishing into one query
            jitted = _attrib.compile_probe(jitted, kid, n_boxes)
        self._jitted[key] = jitted
        from geomesa_tpu import config
        # NB fresh name: the mode closures above capture _get locals (cap,
        # width, …) late — rebinding them here would rewrite the kernel
        lru_cap = max(1, config.KERNEL_CACHE.get())
        while len(self._jitted) > lru_cap:
            self._jitted.popitem(last=False)
        return jitted

    # public API ------------------------------------------------------------

    def count(self, primary_kind, boxes, windows, residual) -> int:
        fn = self._get("count", primary_kind, windows is not None,
                       residual[0] if residual else "none",
                       residual[2] if residual else None,
                       0 if boxes is None else boxes.shape[0],
                       0 if windows is None else windows.shape[0])
        with _attrib.kernel(f"count.{primary_kind}"):
            return int(_fetch(
                fn, self.cols, _dev(boxes), _dev(windows),
                [jnp.asarray(p) for p in residual[1]] if residual else []))

    def mask(self, primary_kind, boxes, windows, residual) -> jnp.ndarray:
        fn = self._get("mask", primary_kind, windows is not None,
                       residual[0] if residual else "none",
                       residual[2] if residual else None,
                       0 if boxes is None else boxes.shape[0],
                       0 if windows is None else windows.shape[0])
        with _trace.span("device_scan"):  # async: consumers block later
            return fn(self.cols, _dev(boxes), _dev(windows),
                      [jnp.asarray(p) for p in residual[1]] if residual else [])

    def count_at(self, primary_kind, boxes, windows, residual,
                 positions: np.ndarray) -> int:
        """Count over candidate positions only (attribute-index pruning)."""
        idxs, nvalid = _pad_positions(positions)
        fn = self._get("count_at", primary_kind, windows is not None,
                       residual[0] if residual else "none",
                       residual[2] if residual else None,
                       0 if boxes is None else boxes.shape[0],
                       0 if windows is None else windows.shape[0],
                       idxs.shape[0])
        return int(_fetch(
            fn, self.cols, _dev(boxes), _dev(windows),
            [jnp.asarray(p) for p in residual[1]] if residual else [],
            jnp.asarray(idxs), nvalid))

    def select_at(self, primary_kind, boxes, windows, residual,
                  positions: np.ndarray):
        """Surviving positions (subset of ``positions``) + count."""
        idxs, nvalid = _pad_positions(positions)
        fn = self._get("select_at", primary_kind, windows is not None,
                       residual[0] if residual else "none",
                       residual[2] if residual else None,
                       0 if boxes is None else boxes.shape[0],
                       0 if windows is None else windows.shape[0],
                       idxs.shape[0])
        out = np.asarray(_fetch(
            fn, self.cols, _dev(boxes), _dev(windows),
            [jnp.asarray(p) for p in residual[1]] if residual else [],
            jnp.asarray(idxs), nvalid))
        cnt = int(out[0])
        sel = out[1: 1 + cnt].astype(np.int64)
        return positions[sel], cnt

    def prepare_counts_multi(self, primary_kind, boxes: np.ndarray, windows,
                             residual):
        """Zero-arg async dispatcher → per-box count device array over the
        FULL table (the batched serving path when range pruning declined).
        B pads to a power of two (EMPTY_BOX rows count zero) to share
        compilations; callers slice the readback to len(boxes)."""
        b = pad_boxes(boxes)
        fn = self._get("count_multi", primary_kind, windows is not None,
                       residual[0] if residual else "none",
                       residual[2] if residual else None,
                       b.shape[0],
                       0 if windows is None else windows.shape[0])
        cols = self.cols
        db, w = _dev(b), _dev(windows)
        rp = [jnp.asarray(p) for p in residual[1]] if residual else []
        return lambda: fn(cols, db, w, rp)

    def counts_multi(self, primary_kind, boxes: np.ndarray, windows,
                     residual) -> np.ndarray:
        """Per-box counts for a (B, 8) box array: one upload, one kernel,
        one readback — B counts for the price of one round trip."""
        tier = max(1, 1 << max(0, (len(boxes) - 1)).bit_length())
        with _attrib.kernel(f"count_multi.{primary_kind}", tier):
            out = np.asarray(_fetch(self.prepare_counts_multi(
                primary_kind, boxes, windows, residual)))
        return out[: len(boxes)]

    def prepare_count(self, primary_kind, boxes, windows, residual):
        """Zero-arg async count dispatcher with all constants pre-staged on
        device. Repeated dispatches pay no host→device transfer and no
        re-planning; the returned device scalar syncs only when the caller
        reads it (prepared-statement pattern; on a tunneled chip this is the
        difference between ~0.1ms and a ~100ms RTT per query)."""
        fn = self._get("count", primary_kind, windows is not None,
                       residual[0] if residual else "none",
                       residual[2] if residual else None,
                       0 if boxes is None else boxes.shape[0],
                       0 if windows is None else windows.shape[0])
        cols = self.cols
        b, w = _dev(boxes), _dev(windows)
        ROUNDS.uploads += len(residual[1]) if residual else 0
        rp = [jnp.asarray(p) for p in residual[1]] if residual else []
        return lambda: fn(cols, b, w, rp)

    def prepare_mask(self, primary_kind, boxes, windows, residual):
        """Zero-arg async mask dispatcher (device constants pre-staged)."""
        fn = self._get("mask", primary_kind, windows is not None,
                       residual[0] if residual else "none",
                       residual[2] if residual else None,
                       0 if boxes is None else boxes.shape[0],
                       0 if windows is None else windows.shape[0])
        cols = self.cols
        b, w = _dev(boxes), _dev(windows)
        rp = [jnp.asarray(p) for p in residual[1]] if residual else []
        return lambda: fn(cols, b, w, rp)

    def _pad_blocks(self, blocks: np.ndarray) -> np.ndarray:
        nb = max(8, 1 << max(0, (len(blocks) - 1)).bit_length())
        out = np.full(nb, -1, dtype=np.int32)
        out[: len(blocks)] = blocks
        return out

    def count_blocks(self, primary_kind, boxes, windows, residual,
                     blocks: np.ndarray, block_size: int) -> int:
        """Exact count scanning only the candidate blocks (range-pruned)."""
        with _attrib.kernel(f"count_blocks.{primary_kind}"):
            return int(_fetch(self.prepare_count_blocks(
                primary_kind, boxes, windows, residual, blocks, block_size)))

    def prepare_count_blocks(self, primary_kind, boxes, windows, residual,
                             blocks: np.ndarray, block_size: int):
        """Zero-arg async pruned-count dispatcher (constants + block ids
        staged on device once)."""
        b = self._pad_blocks(blocks)
        fn = self._get("count_blocks", primary_kind, windows is not None,
                       residual[0] if residual else "none",
                       residual[2] if residual else None,
                       0 if boxes is None else boxes.shape[0],
                       0 if windows is None else windows.shape[0],
                       (b.shape[0], block_size, 0))
        cols = self.cols
        bx, w = _dev(boxes), _dev(windows)
        ROUNDS.uploads += 1 + (len(residual[1]) if residual else 0)
        rp = [jnp.asarray(p) for p in residual[1]] if residual else []
        db = jnp.asarray(b)
        return lambda: fn(cols, bx, w, rp, db)

    def select_blocks(self, primary_kind, boxes, windows, residual,
                      blocks: np.ndarray, block_size: int, capacity: int):
        """(sorted-row indices, true count) scanning only candidate blocks.
        Grows capacity and retries on overflow like ``select``."""
        b = self._pad_blocks(blocks)
        rp = [jnp.asarray(p) for p in residual[1]] if residual else []
        capacity = min(max(1024, capacity), b.shape[0] * block_size)
        while True:
            fn = self._get("select_blocks", primary_kind, windows is not None,
                           residual[0] if residual else "none",
                           residual[2] if residual else None,
                           0 if boxes is None else boxes.shape[0],
                           0 if windows is None else windows.shape[0],
                           (b.shape[0], block_size, capacity))
            out = np.asarray(_fetch(fn, self.cols, _dev(boxes),
                                    _dev(windows), rp, jnp.asarray(b)))
            cnt = int(out[0])
            if cnt <= capacity:
                return out[1: 1 + cnt].astype(np.int64), cnt
            capacity = 1 << int(np.ceil(np.log2(cnt)))

    def prepare_counts_multi_blocks(self, primary_kind, boxes: np.ndarray,
                                    windows, residual, blocks: np.ndarray,
                                    block_size: int):
        """Zero-arg async dispatcher → per-box count device array for a
        whole batch of box-queries over their union candidate blocks (the
        batched serving path — per-query device cost is microseconds once
        the per-dispatch overhead amortizes; pipeline several batches to
        amortize the round trip too)."""
        b = self._pad_blocks(blocks)
        bx = pad_boxes(boxes)
        fn = self._get("count_multi_blocks", primary_kind, windows is not None,
                       residual[0] if residual else "none",
                       residual[2] if residual else None,
                       bx.shape[0],
                       0 if windows is None else windows.shape[0],
                       (b.shape[0], block_size, 0))
        cols = self.cols
        dbx, w = _dev(bx), _dev(windows)
        rp = [jnp.asarray(p) for p in residual[1]] if residual else []
        db = jnp.asarray(b)
        return lambda: fn(cols, dbx, w, rp, db)

    def counts_multi_blocks(self, primary_kind, boxes: np.ndarray, windows,
                            residual, blocks: np.ndarray,
                            block_size: int) -> np.ndarray:
        """Blocking counterpart of ``prepare_counts_multi_blocks``."""
        tier = max(1, 1 << max(0, (len(boxes) - 1)).bit_length())
        with _attrib.kernel(f"count_multi_blocks.{primary_kind}", tier):
            out = np.asarray(_fetch(self.prepare_counts_multi_blocks(
                primary_kind, boxes, windows, residual, blocks, block_size)))
        return out[: len(boxes)]

    def prepare_density_compact(self, primary_kind, boxes, windows, residual,
                                grid_bbox, width: int, height: int,
                                cap: int, wname: Optional[str]):
        """Zero-arg dispatcher → ((H, W) grid device array, count scalar).
        ``cap`` must be >= the match count (size it from a count query)."""
        fn = self._get("density_compact", primary_kind, windows is not None,
                       residual[0] if residual else "none",
                       residual[2] if residual else None,
                       0 if boxes is None else boxes.shape[0],
                       0 if windows is None else windows.shape[0],
                       (cap, width, height, wname))
        cols = self.cols
        bx, w = _dev(boxes), _dev(windows)
        rp = [jnp.asarray(p) for p in residual[1]] if residual else []
        g = jnp.asarray(np.asarray(grid_bbox, dtype=np.float32))
        return lambda: fn(cols, bx, w, rp, g)

    def prepare_density_blocks(self, primary_kind, boxes, windows, residual,
                               grid_bbox, width: int, height: int,
                               blocks: np.ndarray, block_size: int,
                               wname: Optional[str]):
        """Zero-arg dispatcher for the range-pruned heat-map."""
        b = self._pad_blocks(blocks)
        fn = self._get("density_blocks", primary_kind, windows is not None,
                       residual[0] if residual else "none",
                       residual[2] if residual else None,
                       0 if boxes is None else boxes.shape[0],
                       0 if windows is None else windows.shape[0],
                       (b.shape[0], block_size, 0, width, height, wname))
        cols = self.cols
        bx, w = _dev(boxes), _dev(windows)
        rp = [jnp.asarray(p) for p in residual[1]] if residual else []
        g = jnp.asarray(np.asarray(grid_bbox, dtype=np.float32))
        db = jnp.asarray(b)
        return lambda: fn(cols, bx, w, rp, g, db)

    # polygon-edge pad: far-away horizontal edges (ey1 == ey2 → no crossing;
    # orientation signs large and same → certain-miss) so padded lanes never
    # create hits or uncertainty
    _EDGE_PAD = np.array([1e9, 1e9, 2e9, 1e9], dtype=np.float32)

    def intersects_band_blocks(self, primary_kind, boxes, windows, residual,
                               edges: np.ndarray, blocks: np.ndarray,
                               block_size: int, unc_cap: int = 4096):
        """(certain_hit_count, uncertain_row_positions) for exact
        segment-feature × polygon intersects over candidate blocks. The
        uncertain positions (rows within the f32 certainty band of a
        boundary) need the host's exact f64 refine; returns None for the
        positions when they overflowed ``unc_cap`` (caller falls back to the
        full host refine)."""
        b = self._pad_blocks(blocks)
        ne = max(4, 1 << max(0, (len(edges) - 1)).bit_length())
        ep = np.tile(self._EDGE_PAD, (ne, 1))
        ep[: len(edges)] = edges
        fn = self._get("intersects_band_blocks", primary_kind,
                       windows is not None,
                       residual[0] if residual else "none",
                       residual[2] if residual else None,
                       0 if boxes is None else boxes.shape[0],
                       0 if windows is None else windows.shape[0],
                       (b.shape[0], block_size, 0, unc_cap, ne))
        rp = [jnp.asarray(p) for p in residual[1]] if residual else []
        out = np.asarray(_fetch(fn, self.cols, _dev(boxes), _dev(windows),
                                rp, jnp.asarray(ep), jnp.asarray(b)))
        certain = int(out[0])
        n_unc = int(out[1])
        if n_unc > unc_cap:
            return certain, None
        return certain, out[2: 2 + n_unc].astype(np.int64)

    def topk_nearest_blocks(self, primary_kind, boxes, windows, residual,
                            qx: float, qy: float, m: int,
                            blocks: np.ndarray, block_size: int):
        """Pruned variant of ``topk_nearest``: distances + top_k over the
        candidate blocks only."""
        b = self._pad_blocks(blocks)
        m = min(m, b.shape[0] * block_size)
        fn = self._get("topk_blocks", primary_kind, windows is not None,
                       residual[0] if residual else "none",
                       residual[2] if residual else None,
                       0 if boxes is None else boxes.shape[0],
                       0 if windows is None else windows.shape[0],
                       (b.shape[0], block_size, 0, m))
        q = jnp.asarray(np.array([qx, qy], dtype=np.float32))
        rp = [jnp.asarray(p) for p in residual[1]] if residual else []
        with _attrib.kernel(f"topk_blocks.{primary_kind}", b.shape[0]):
            vals, idxs = _fetch(fn, self.cols, _dev(boxes), _dev(windows),
                                rp, q, jnp.asarray(b))
        return np.asarray(vals), np.asarray(idxs)

    def topk_nearest(self, primary_kind, boxes, windows, residual,
                     qx: float, qy: float, m: int):
        """(distances_m f32, sorted-order positions int32) of the m nearest
        masked rows to (qx, qy) — one kernel, one small readback. Distances
        are +inf past the number of matching rows."""
        fn = self._get("topk", primary_kind, windows is not None,
                       residual[0] if residual else "none",
                       residual[2] if residual else None,
                       0 if boxes is None else boxes.shape[0],
                       0 if windows is None else windows.shape[0], m)
        q = jnp.asarray(np.array([qx, qy], dtype=np.float32))
        rp = [jnp.asarray(p) for p in residual[1]] if residual else []
        with _attrib.kernel(f"topk.{primary_kind}", m):
            vals, idxs = _fetch(fn, self.cols, _dev(boxes), _dev(windows),
                                rp, q)
        return np.asarray(vals), np.asarray(idxs)

    def select(self, primary_kind, boxes, windows, residual, capacity: int):
        """Returns (sorted-row indices ndarray, true_count) in one roundtrip.
        Grows capacity and retries on overflow (fixed-capacity +
        overflow-retry per SURVEY.md §7 hard-parts)."""
        rp = [jnp.asarray(p) for p in residual[1]] if residual else []
        while True:
            fn = self._get("select_packed", primary_kind, windows is not None,
                           residual[0] if residual else "none",
                           residual[2] if residual else None,
                           0 if boxes is None else boxes.shape[0],
                           0 if windows is None else windows.shape[0],
                           capacity)
            out = np.asarray(_fetch(fn, self.cols, _dev(boxes),
                                    _dev(windows), rp))
            cnt = int(out[0])
            if cnt <= capacity:
                return out[1: 1 + cnt].astype(np.int64), cnt
            capacity = 1 << int(np.ceil(np.log2(cnt)))


def _dev(a):
    if a is None:
        return None
    ROUNDS.uploads += 1
    return jnp.asarray(a)


def _pad_positions(positions: np.ndarray):
    """Pad a candidate-position array to the next power of two (shared jit
    signatures across queries); padding rows point at row 0 and are masked
    off by the valid-length compare."""
    n = len(positions)
    cap = max(8, 1 << max(0, (n - 1)).bit_length())
    out = np.zeros(cap, dtype=np.int32)
    out[:n] = positions
    return out, np.int32(n)


# -- padding helpers --------------------------------------------------------

_I31MAX = (1 << 31) - 1
# fp62 empty box: lo bound = +max, hi bound = 0 — matches nothing
EMPTY_BOX = np.array([_I31MAX, _I31MAX, 0, 0, _I31MAX, _I31MAX, 0, 0], dtype=np.int32)
EMPTY_WINDOW = np.array([1, 0, 0, 0], dtype=np.int32)    # bin_lo > bin_hi


def pad_boxes(boxes: np.ndarray, min_size: int = 1) -> np.ndarray:
    """Pad (B,8) int32 fp62 box array to the next power-of-two count."""
    b = max(min_size, len(boxes))
    size = 1 << (b - 1).bit_length()
    out = np.tile(EMPTY_BOX, (size, 1))
    if len(boxes):
        out[: len(boxes)] = boxes
    return out


def pad_windows(windows: np.ndarray, min_size: int = 1) -> np.ndarray:
    b = max(min_size, len(windows))
    size = 1 << (b - 1).bit_length()
    out = np.tile(EMPTY_WINDOW, (size, 1))
    if len(windows):
        out[: len(windows)] = windows
    return out
