"""Spatial index implementations: Z2 / Z3 / XZ2 / XZ3.

≙ reference index.index.{z2,z3} key spaces (Z3IndexKeySpace.scala:34 etc.).
Each index owns a device-resident projection of the table sorted in its key
order (epoch-major for the temporal variants — the epoch bin is the row-key
prefix exactly as in the reference's ``[shard][epoch:2][z:8]`` layout), plus
host-side sorted key arrays for range pruning, and produces IndexScanPlans:

  - spatial constraint → padded int31 boxes, loose (cell cover) + strict
    (cell interior) — the contained/overlapping-range distinction
  - temporal constraint → exact (bin, offset) windows (Z3Filter.timeInBounds)
  - leftover predicates → device residual (compiled) + host residual

The scan itself is a full-table fused mask (bandwidth-bound, fast on TPU);
the sorted layout + host key arrays enable block-range pruning (searchsorted
over the reference-style z-range cover) which the planner can enable for
low-selectivity queries.
"""

from __future__ import annotations

import sys
from typing import Dict, List, Optional, Tuple

import numpy as np

from geomesa_tpu.curves.binnedtime import TimePeriod, max_offset, time_to_binned_time
from geomesa_tpu.curves.normalize import NormalizedLat, NormalizedLon
from geomesa_tpu.curves.sfc import Z2SFC, Z3SFC
from geomesa_tpu.curves.xz import XZ2SFC, XZ3SFC
from geomesa_tpu.curves import zorder
from geomesa_tpu.features.table import FeatureTable, StringColumn
from geomesa_tpu.filter import extract, ir
from geomesa_tpu.filter.extract import extract_bboxes, extract_intervals
from geomesa_tpu.index.api import IndexScanPlan
from geomesa_tpu.index.device import DeviceTable, fp62_lat, fp62_lon, host_planes
from geomesa_tpu.index.scan import (ModuleKernelCache, ScanKernels, pad_boxes,
                                    pad_windows, split_residual,
                                    compile_residual)

# Above this row count the index-key sort and row reorder run on the
# accelerator (3×21-bit int32 key planes through lax.sort + one fused gather)
# instead of a single-core host lexsort — ~80× faster at 100M rows.
# DEVICE_SORT_MIN_ROWS resolves through the config registry on each access
# (PEP 562) so runtime overrides apply; tests may monkeypatch it directly.
from geomesa_tpu import config as _config


def __getattr__(name: str):
    if name == "DEVICE_SORT_MIN_ROWS":
        return _config.DEVICE_SORT_MIN.get()
    raise AttributeError(name)

_MASK21 = (1 << 21) - 1


def _stream_encode_upload(encode_chunk, n: int, chunk_rows: int,
                          key_names: Optional[List[str]] = None,
                          shard_devices=None):
    """Chunked native encode overlapped with host→device upload.

    ≙ the latency-hiding of the reference's ``AbstractBatchScan`` pipeline
    (SURVEY §2.12 row 8), applied to the build path: a background thread
    streams chunk i's planes to the device while the C++ encoder (which
    releases the GIL) works on chunk i+1, so encode time and transfer time
    overlap instead of summing. Per-plane chunks concatenate ON DEVICE
    (transient ~2x HBM for the planes, freed before the sort gather).

    With ``shard_devices`` (≥2) the sort-key planes of chunk i additionally
    land round-robin on ``shard_devices[i % D]`` so the mesh-sharded sort
    starts with its inputs already distributed — upload and shard-sort
    pipeline instead of re-scattering after a single-device concat. The
    sort-only planes (zhi/zlo) then skip the default device entirely.

    ``encode_chunk(lo, hi)`` → plane dict or None (native decline).
    Returns ({plane: device array}, [host-kept chunk dicts], key_shards)
    where key_shards is None without sharding, else a per-device list of
    ``(row_offset, [key plane arrays])`` chunks; returns None when any
    chunk declines — the caller falls back to the single-shot path.
    """
    import queue
    import threading

    import jax
    import jax.numpy as jnp

    sharding = (shard_devices is not None and len(shard_devices) >= 2
                and key_names is not None)
    key_shards: Optional[List[list]] = \
        [[] for _ in shard_devices] if sharding else None

    q: "queue.Queue" = queue.Queue(maxsize=2)
    uploaded: List[dict] = []
    state = {"error": None}

    def uploader():
        # a device_put failure (e.g. HBM OOM) must record the error and KEEP
        # DRAINING: exiting early leaves the producer blocked forever on the
        # bounded queue (deadlocked build, exception swallowed)
        while True:
            item = q.get()
            if item is None:
                return
            if state["error"] is not None:
                continue
            off, enc = item
            try:
                if sharding:
                    d = (off // chunk_rows) % len(shard_devices)
                    key_shards[d].append((off, [
                        jax.device_put(enc[k], shard_devices[d])
                        for k in key_names]))
                    uploaded.append({k: jax.device_put(v)
                                     for k, v in enc.items()
                                     if k not in ("zhi", "zlo")})
                else:
                    uploaded.append({k: jax.device_put(v)
                                     for k, v in enc.items()})
            except BaseException as e:  # noqa: BLE001 - re-raised below
                state["error"] = e

    th = threading.Thread(target=uploader, daemon=True)
    th.start()
    host_kept: List[dict] = []
    failed = False
    try:
        for a in range(0, n, chunk_rows):
            if state["error"] is not None:
                break
            enc = encode_chunk(a, min(n, a + chunk_rows))
            if enc is None:
                failed = True
                break
            # z (and bin16 where present) stay host-side for range pruning;
            # keep refs BEFORE the device put consumes the dict
            host_kept.append({k: enc[k] for k in ("z", "bin16")
                              if k in enc})
            enc.pop("z", None)
            q.put((a, enc))
    finally:
        q.put(None)
        th.join()
    if state["error"] is not None:
        raise state["error"]
    if failed or not uploaded:
        return None
    dev = {k: (uploaded[0][k] if len(uploaded) == 1
               else jnp.concatenate([u[k] for u in uploaded]))
           for k in uploaded[0]}
    return dev, host_kept, key_shards


def _split63(v: np.ndarray) -> List[np.ndarray]:
    """Split non-negative int64 keys (< 2^63) into three 21-bit int32 planes
    (major → minor) so the device sort never needs 64-bit lanes."""
    v = np.asarray(v, dtype=np.int64)
    return [((v >> 42) & _MASK21).astype(np.int32),
            ((v >> 21) & _MASK21).astype(np.int32),
            (v & _MASK21).astype(np.int32)]


def _sort_perm_fn(ks):
    """Stable sort permutation from padded key planes (row iota rides as the
    final key, making the order total == a stable host lexsort)."""
    import jax.numpy as jnp
    from jax import lax

    iota = lax.iota(jnp.int32, ks[0].shape[0])
    out = lax.sort(tuple(ks) + (iota,), num_keys=len(ks) + 1)
    return out[-1]


# Build-path jit caches: previously bare module globals that pinned one
# compilation per padded signature forever; now bounded shape-keyed LRUs
# (GEOMESA_TPU_KERNEL_CACHE) counted in the kernels.compiled gauge.
_SORT_PERM_CACHE = ModuleKernelCache("build.sort_perm")
_ROW_GATHER_CACHE = ModuleKernelCache("build.row_gather")
_SORT_GATHER_CACHE = ModuleKernelCache("build.sort_gather")


def _sort_perm(padded_keys):
    """Shape-keyed jit shared across every index build in the process (the
    per-call-closure version re-traced on each build); one cache entry per
    (plane count, padded length) signature."""
    import jax
    key = (len(padded_keys), int(padded_keys[0].shape[0]))
    fn = _SORT_PERM_CACHE.get(key, lambda: jax.jit(_sort_perm_fn))
    return fn(tuple(padded_keys))


def device_sort_perm(keys: List[np.ndarray], type_name: Optional[str] = None):
    """Sort permutation computed on device from int32 key planes.

    On a multi-device mesh (and above GEOMESA_TPU_SHARD_SORT_MIN rows) the
    sort shards across devices (parallel.dist.mesh_sort_perm) — bitwise the
    same permutation; a 1-device mesh takes the single-device path below.
    Keys are padded to a power of two with int32-max sentinels (shared jit
    signatures across sizes).
    """
    import jax.numpy as jnp

    n = len(keys[0])
    from geomesa_tpu.parallel import dist as _dist
    if _dist.mesh_sort_enabled(n):
        return _dist.mesh_sort_perm([np.ascontiguousarray(k) for k in keys],
                                    type_name=type_name)
    cap = 1 << max(0, (n - 1)).bit_length()
    padded = []
    for k in keys:
        p = np.full(cap, np.iinfo(np.int32).max, dtype=np.int32)
        p[:n] = k
        padded.append(jnp.asarray(p))
    return _sort_perm(padded)[:n]


def _row_gather(dev_perm, idx: np.ndarray) -> np.ndarray:
    """Gather table rows for sorted positions on device (pow2-padded so
    compilations and transfer shapes are shared across result sizes)."""
    import jax
    import jax.numpy as jnp

    if len(idx) == 0:
        return np.empty(0, dtype=np.int64)
    cap = max(8, 1 << max(0, len(idx) - 1).bit_length())
    pad = np.zeros(cap, np.int32)
    pad[: len(idx)] = idx
    key = (int(dev_perm.shape[0]), cap)
    fn = _ROW_GATHER_CACHE.get(key, lambda: jax.jit(lambda p, i: p[i]))
    out = np.asarray(fn(dev_perm, jnp.asarray(pad)))
    return out[: len(idx)].astype(np.int64)


def _as_query_column(name: str, gathered, xp):
    """Shared build-plane → device-column rename/cast rule (one home for both
    the host small-table gather and the traced device gather): bin16 lands as
    an int32 ``bin`` column; sort-key planes (zhi/zlo) are not query columns."""
    if name in ("zhi", "zlo"):
        return None, None
    if name == "bin16":
        return "bin", gathered.astype(xp.int32)
    return name, gathered


def _native_sort_gather(keys, cols, n: int):
    """One fused device program: sort padded keys → perm, gather every query
    column through it, cast bin16 → int32. Cached per (shapes, n) signature
    so repeated builds share compilations without pinning every size tier."""
    import functools

    import jax
    import jax.numpy as jnp

    def build():
        @functools.partial(jax.jit, static_argnames=("n",))
        def fn(keys, cols, n):
            cap = 1 << max(0, (n - 1)).bit_length()
            padded = tuple(
                jnp.pad(k, (0, cap - n),
                        constant_values=np.array(np.iinfo(k.dtype).max,
                                                 dtype=k.dtype))
                for k in keys)
            perm = _sort_perm_fn(padded)[:n]
            out = {}
            for name, v in cols.items():
                out_name, g = _as_query_column(name, v[perm], jnp)
                if out_name is not None:
                    out[out_name] = g
            return perm, out

        return fn

    key = (n, len(keys),
           tuple(sorted((name, str(v.dtype)) for name, v in cols.items())))
    return _SORT_GATHER_CACHE.get(key, build)(keys, cols, n)


def _perm_gather_cols(dev_perm, cols, n: int):
    """Gather query columns through an already-computed device permutation
    (the mesh-sharded sort path, where the perm comes from
    parallel.dist.mesh_sort_perm instead of the fused sort_gather program)."""
    import jax
    import jax.numpy as jnp

    def build():
        def fn(perm, cols):
            out = {}
            for name, v in cols.items():
                out_name, g = _as_query_column(name, v[perm], jnp)
                if out_name is not None:
                    out[out_name] = g
            return out

        return jax.jit(fn)

    key = ("perm_gather", n,
           tuple(sorted((name, str(v.dtype)) for name, v in cols.items())))
    return _SORT_GATHER_CACHE.get(key, build)(dev_perm, cols)


def _strip_handled(f: ir.Filter, geom: Optional[str], dtg: Optional[str],
                   points: bool) -> Optional[ir.Filter]:
    """Residual after removing predicates the primary boxes/windows enforce
    exactly.

    A spatial node drops when its box extraction IS the predicate: BBox
    (envelope-overlap semantics, exact for points and extents alike via the
    fp62 envelope planes) and, for point layers only, exact-extracting
    Intersects (point/rectangle literals). Temporal nodes on ``dtg`` always
    drop (windows are exact). OR-rooted filters keep the whole filter as
    residual (the boxes/windows become a superset prefilter) — the
    conservative analogue of the reference's DNF expansion fallback
    (FilterSplitter.scala:61-103).
    """
    if isinstance(f, ir.Or):
        return f
    children = f.children if isinstance(f, ir.And) else (f,)
    rest: List[ir.Filter] = []
    for c in children:
        if isinstance(c, (ir.BBox, ir.Intersects, ir.Contains, ir.Within, ir.Dwithin)) \
                and (geom is None or c.attr == geom):
            if isinstance(c, ir.BBox):
                continue  # envelope semantics: primary boxes are exact
            if points and extract_bboxes(c, geom).exact:
                continue  # point-in-rectangle: primary boxes are exact
            rest.append(c)
        elif isinstance(c, ir.During) and c.attr == dtg:
            continue  # exact via windows
        elif isinstance(c, ir.Cmp) and c.attr == dtg and isinstance(c.value, (int, np.integer)):
            continue  # exact via windows
        else:
            rest.append(c)
    return ir.and_filters(rest) if rest else None


def _boxes_fp62(boxes) -> np.ndarray:
    """User-space boxes → (B, 8) int32 fp62 query planes:
    [qxlo_hi, qxlo_lo, qxhi_hi, qxhi_lo, qylo_hi, qylo_lo, qyhi_hi, qyhi_lo].
    Device comparisons against these reproduce f64 bounds exactly (device.fp62)."""
    out = np.empty((len(boxes), 8), dtype=np.int32)
    for i, (xmin, ymin, xmax, ymax) in enumerate(boxes):
        xlo = fp62_lon(xmin)
        xhi = fp62_lon(xmax)
        ylo = fp62_lat(ymin)
        yhi = fp62_lat(ymax)
        out[i] = (xlo[0], xlo[1], xhi[0], xhi[1], ylo[0], ylo[1], yhi[0], yhi[1])
    return out


class _DeltaKeyShim:
    """Minimal stand-in passed to an index class's ``_sort_keys`` to compute
    a delta run's key planes without building a full index over the delta
    table (``_sort_keys`` reads table/sft/dtg/period/geom and writes its key
    arrays — ``_z``/``_xz``/``_bins``/``_sfc`` — onto ``self``)."""

    def __init__(self, sft, table, geom, dtg, period):
        self.sft = sft
        self.table = table
        self.geom = geom
        self.dtg = dtg
        self.period = period


class BaseSpatialIndex:
    """Shared machinery: device table, kernels, plan construction."""

    name: str = "base"
    temporal: bool = False
    points: bool = True

    def __init__(self, sft, table: FeatureTable):
        self.sft = sft
        self.table = table
        self.geom = sft.geometry_attribute.name if sft.geometry_attribute else None
        dtg = sft.dtg_attribute
        self.dtg = dtg.name if dtg else None
        self.period = TimePeriod.parse(sft.z3_interval) if self.dtg else None
        self._perm_cache: Optional[np.ndarray] = None
        self._dev_perm = None
        n = len(table)
        from geomesa_tpu.obs.profiling import PROGRESS as _progress
        if not self._build_native():
            keys = self._sort_keys()
            if keys is None:
                self._perm_cache = np.arange(n, dtype=np.int64)
                self.device = DeviceTable.build(table, self._perm_cache, self.period)
            elif n >= sys.modules[__name__].DEVICE_SORT_MIN_ROWS and all(
                    k.dtype == np.int32 for k in keys):
                with _progress.phase("device_sort", rows=n,
                                     type_name=sft.name):
                    self._dev_perm = device_sort_perm(keys,
                                                      type_name=sft.name)
                with _progress.phase("upload_gather", rows=n,
                                     type_name=sft.name):
                    self.device = DeviceTable.build_on_device(
                        table, self._dev_perm, self.period)
                self._prefetch_perm()
            else:
                # np.lexsort sorts by LAST key first → reverse to major-first
                with _progress.phase("host_sort", rows=n,
                                     type_name=sft.name):
                    self._perm_cache = np.lexsort(
                        tuple(reversed(keys))).astype(np.int64)
                with _progress.phase("upload_gather", rows=n,
                                     type_name=sft.name):
                    self.device = DeviceTable.build(
                        table, self._perm_cache, self.period)
        import time as _time
        _t = _time.perf_counter()
        self.kernels = ScanKernels(self.device.columns)
        if hasattr(self, "build_stages"):
            self.build_stages["warm_shapes_s"] = round(
                _time.perf_counter() - _t, 2)
        self.vocabs = {
            name: col.vocab for name, col in table.columns.items()
            if isinstance(col, StringColumn)
        }

    def _join_prefetch(self) -> None:
        """Wait for the background perm/keys prefetch (if any) to finish.
        Every lazy accessor calls this first — otherwise a query arriving
        while the prefetch is mid-gather would see a not-yet-set cache and
        redo the same multi-hundred-ms gather synchronously (the r4
        plan-stage regression at 10M scale)."""
        import threading
        t = getattr(self, "_perm_thread", None)
        if t is not None and t is not threading.current_thread():
            t.join()
            self._perm_thread = None

    @property
    def perm(self) -> np.ndarray:
        """Host copy of the index sort permutation (sorted pos → table row);
        downloaded from the device lazily on the large-table build path (a
        background prefetch started at build time usually has it ready)."""
        if self._perm_cache is None:
            self._join_prefetch()
        if self._perm_cache is None:
            self._perm_cache = np.asarray(self._dev_perm).astype(np.int64)
        return self._perm_cache

    def _host_sorted_keys(self) -> None:
        """Derive the sorted host pruning keys WITHOUT downloading the
        device perm. The index order is (bin, key, row); row only breaks
        ties between EQUAL keys, so the sorted key *values* are exactly
        np.sort per bin segment — ~6s of host sorts at 100M versus a
        400MB perm download through a tunnel whose downlink runs 10-100×
        slower than its uplink (measured 2-25MB/s down vs 30-280MB/s up)."""
        bins = getattr(self, "_bins", None)
        order = None
        if bins is not None:
            # one stable argsort of the (small-dtype) bins, then per-segment
            # value sorts — O(N log N) regardless of how many bins exist
            order = np.argsort(bins, kind="stable")
            self._sorted_bins = np.asarray(bins)[order]
            segs = self._bin_segments()
        for attr, src in (("_sorted_z", getattr(self, "_z", None)),
                          ("_sorted_xz", getattr(self, "_xz", None))):
            if src is None:
                continue
            if order is None:
                setattr(self, attr, np.sort(src))
            else:
                out = src[order]
                for i in range(len(segs.bins)):
                    out[segs.starts[i]: segs.starts[i + 1]].sort()
                setattr(self, attr, out)

    def _prefetch_perm(self) -> None:
        """Overlap the derived host pruning keys (sorted z/bins + bin
        segments) with whatever the caller does next after the build, so
        the first query's prepare is ~ms. The device perm itself is NOT
        downloaded here — ``map_rows`` gathers small result sets on device
        and the ``perm`` property downloads in full only on demand."""
        import threading

        def fetch():
            try:
                self._host_sorted_keys()
            except Exception:
                pass  # the lazy properties will retry synchronously

        self._perm_thread = threading.Thread(target=fetch, daemon=True)
        self._perm_thread.start()

    def map_rows(self, idx: np.ndarray) -> np.ndarray:
        """Sorted-position → table-row mapping for query results. Prefers
        the cached host perm; small sets gather against the device-resident
        perm (a full perm download is 100s of MB through the slow downlink
        — only huge hydrations warrant it)."""
        idx = np.asarray(idx, dtype=np.int64)
        if self._perm_cache is not None or self._dev_perm is None \
                or len(idx) > (1 << 20):
            return self.perm[idx]
        return _row_gather(self._dev_perm, idx)

    # subclasses supply the sort keys ---------------------------------------

    def _sort_keys(self) -> Optional[List[np.ndarray]]:
        """Int32 key planes, major → minor (None = natural table order)."""
        raise NotImplementedError

    def _build_native(self) -> bool:
        """Fused native-encode build (geomesa_tpu.native): the host runs one
        C++ pass producing every device plane + sort key, so the table builds
        with a single upload + one device sort/gather program. Returns False
        when unsupported — the numpy path runs instead."""
        return False

    def _stream_build(self, encode_chunk, key_names: List[str], n: int,
                      extra: Dict[str, np.ndarray]):
        """Streamed native build when ``n`` crosses the chunk and
        device-sort thresholds. True = built, False = a chunk declined the
        native path (caller falls back to numpy), None = below thresholds
        (caller runs the single-shot native path)."""
        from geomesa_tpu import config as _cfg
        chunk = _cfg.BUILD_STREAM_CHUNK.get()
        if not (n > chunk
                and n >= sys.modules[__name__].DEVICE_SORT_MIN_ROWS):
            return None
        import time as _time
        from geomesa_tpu.obs.profiling import PROGRESS as _progress
        from geomesa_tpu.parallel import dist as _dist
        shard_devices = _dist.shard_devices() \
            if _dist.mesh_sort_enabled(n) else None
        t0 = _time.perf_counter()
        with _progress.phase("encode_upload", rows=n,
                             type_name=self.sft.name):
            res = _stream_encode_upload(encode_chunk, n, chunk,
                                        key_names=key_names,
                                        shard_devices=shard_devices)
        if res is None:
            return False
        dev, host_kept, key_shards = res
        self._z = np.concatenate([h["z"] for h in host_kept])
        if "bin16" in host_kept[0]:
            self._bins = np.concatenate([h["bin16"] for h in host_kept])
        self.build_stages = {"encode_upload_overlap_s": round(
            _time.perf_counter() - t0, 2)}
        self._finish_native(dev, key_names, extra, key_shards=key_shards)
        return True

    def _finish_native(self, enc: dict, key_names: List[str],
                       extra: Dict[str, np.ndarray],
                       key_shards=None) -> None:
        """Upload native-encoded planes, sort on device, gather.

        ``enc``: native encode output; ``key_names``: sort-key entries of
        ``enc`` major→minor (padded host-side to a power of two with max
        sentinels so jit signatures are shared per size tier); ``extra``:
        remaining host planes (attributes, visibility); ``key_shards``:
        key planes already distributed across the sort mesh by the streamed
        upload (round-robin chunks) — triggers the mesh-sharded sort."""
        import jax
        import jax.numpy as jnp

        n = len(self.table)
        upload = dict(enc)
        upload.pop("z", None)  # host-only (range-pruning searchsorted)
        upload.update(extra)

        if n < sys.modules[__name__].DEVICE_SORT_MIN_ROWS:
            # small tables: host lexsort + host gather (device sort overhead
            # isn't worth it; keeps the native path exercised by unit tests)
            keys = [upload[name] for name in key_names]
            perm = np.lexsort(tuple(reversed(keys)))
            self._perm_cache = perm.astype(np.int64)
            cols = {}
            for name, v in upload.items():
                out_name, g = _as_query_column(name, v[perm], np)
                if out_name is not None:
                    cols[out_name] = jnp.asarray(g)
            self.device = DeviceTable(n, cols)
            return

        from geomesa_tpu.parallel import dist as _dist
        if key_shards is not None or _dist.mesh_sort_enabled(n):
            self._finish_native_mesh(upload, key_names, key_shards, n)
            return

        keys = [upload.pop(name) if name in ("zhi", "zlo") else upload[name]
                for name in key_names]
        # async uploads: dispatch all puts UNPADDED (the build program pads
        # to the power-of-two sort shape on DEVICE — ~28% less key traffic
        # through the host link and no host pad pass; the program is keyed
        # by n already, so device-side padding adds no compilations)
        import time as _time
        from geomesa_tpu.obs.profiling import PROGRESS as _progress
        t0 = _time.perf_counter()
        with _progress.phase("upload", rows=n, type_name=self.sft.name):
            dev_keys = [jax.device_put(k) for k in keys]
            dev_cols = {k: jax.device_put(v) for k, v in upload.items()}
            jax.block_until_ready(dev_keys + list(dev_cols.values()))
        t1 = _time.perf_counter()
        with _progress.phase("sort_gather", rows=n, type_name=self.sft.name):
            self._dev_perm, cols = _native_sort_gather(
                tuple(dev_keys), dev_cols, n)
            jax.block_until_ready(self._dev_perm)
        t2 = _time.perf_counter()
        # per-stage build timings (≙ the profile the reference exposes via
        # MethodProfiling around its writers); bench surfaces these so a
        # slow build is attributable: upload is tunnel-bandwidth, sort is
        # device + compile (persistent-cached after the first run)
        mb = sum(k.nbytes for k in keys) / 1e6 \
            + sum(v.nbytes for v in upload.values()) / 1e6
        self.build_stages = dict(getattr(self, "build_stages", {}))
        self.build_stages.update({
            "upload_s": round(t1 - t0, 2), "upload_mb": round(mb, 1),
            "sort_gather_s": round(t2 - t1, 2)})
        self.device = DeviceTable(n, cols)
        self._prefetch_perm()

    def _finish_native_mesh(self, upload: dict, key_names: List[str],
                            key_shards, n: int) -> None:
        """Mesh-sharded variant of the native finish: the sort permutation
        comes from parallel.dist.mesh_sort_perm (per-shard lax.sort +
        splitter exchange + per-partition merge), then the query columns
        gather through it on the default device. Bitwise the same
        permutation as the single-device program."""
        import time as _time

        import jax

        from geomesa_tpu.obs.profiling import PROGRESS as _progress
        from geomesa_tpu.parallel import dist as _dist

        stages: Dict[str, float] = {}
        if key_shards is not None:
            # streamed path: key planes are already shard-resident; zhi/zlo
            # never touched the default device
            upload.pop("zhi", None)
            upload.pop("zlo", None)
            perm = _dist.mesh_sort_perm(shards=key_shards, n=n,
                                        type_name=self.sft.name,
                                        stages=stages)
        else:
            planes = [np.asarray(upload.pop(name)) if name in ("zhi", "zlo")
                      else np.asarray(upload[name]) for name in key_names]
            perm = _dist.mesh_sort_perm(planes, type_name=self.sft.name,
                                        stages=stages)
        t0 = _time.perf_counter()
        with _progress.phase("upload", rows=n, type_name=self.sft.name):
            dev_cols = {k: jax.device_put(v) for k, v in upload.items()}
            jax.block_until_ready(list(dev_cols.values()))
        t1 = _time.perf_counter()
        with _progress.phase("upload_gather", rows=n,
                             type_name=self.sft.name):
            self._dev_perm = perm
            cols = _perm_gather_cols(perm, dev_cols, n)
            jax.block_until_ready(self._dev_perm)
        t2 = _time.perf_counter()
        mb = sum(v.nbytes for v in upload.values()) / 1e6
        self.build_stages = dict(getattr(self, "build_stages", {}))
        self.build_stages.update(stages)
        self.build_stages.update({
            "upload_s": round(t1 - t0, 2), "upload_mb": round(mb, 1),
            "mesh_gather_s": round(t2 - t1, 2)})
        self.device = DeviceTable(n, cols)
        self._prefetch_perm()

    # incremental merge builds ----------------------------------------------

    @classmethod
    def merge_from(cls, old: "BaseSpatialIndex", merged_table: FeatureTable,
                   n_old: int) -> "BaseSpatialIndex":
        """Incremental (LSM-merge) build: ``merged_table`` = ``old.table``
        followed by ``n_delta`` appended rows. Instead of re-sorting all
        ``n_old + n_delta`` keys, sort only the delta run, rank it into the
        resident sorted run (per-bin searchsorted — only the touched bin
        segments are walked), and scatter both runs into the merged layout:
        host block metadata by direct placement, device columns through one
        merge-scatter program that moves only delta-sized data over the
        host link. The result is bitwise identical (perm, sorted planes,
        device columns) to a full rebuild, because the merged order equals
        the stable lexsort of the concatenated keys: residents keep their
        relative order, delta rows keep theirs, and ties go to residents
        (smaller original row index)."""
        from geomesa_tpu.obs.profiling import PROGRESS as _progress

        import time as _time

        n_new = len(merged_table)
        n_delta = n_new - n_old
        sft = old.sft

        self = cls.__new__(cls)
        self.sft = sft
        self.table = merged_table
        self.geom = old.geom
        self.dtg = old.dtg
        self.period = old.period
        self._perm_cache = None
        self._dev_perm = None
        self._bin_segs = None

        with _progress.phase("merge", rows=n_new, type_name=sft.name):
            t0 = _time.perf_counter()
            delta_table = merged_table.take(
                np.arange(n_old, n_new, dtype=np.int64))
            shim = _DeltaKeyShim(sft, delta_table, old.geom, old.dtg,
                                 old.period)
            keys_d = cls._sort_keys(shim)
            if hasattr(shim, "_sfc"):
                self._sfc = shim._sfc

            touched_bins = 0
            if keys_d is None:
                # natural order (FullScanIndex): delta appends after residents
                p_d = np.arange(n_delta, dtype=np.int64)
                r = np.full(n_delta, n_old, dtype=np.int64)
            else:
                p_d = np.lexsort(tuple(reversed(keys_d))).astype(np.int64)
                z_d = getattr(shim, "_z", None)
                xz_d = getattr(shim, "_xz", None)
                sec_d = np.asarray(z_d if z_d is not None else xz_d)
                sec_sorted_d = sec_d[p_d]
                bins_d = getattr(shim, "_bins", None)
                old_sec = old.sorted_z if z_d is not None else old.sorted_xz
                if bins_d is not None:
                    bins_d = np.asarray(bins_d)
                    bins_sorted_d = bins_d[p_d]
                    old_bins = old.sorted_bins
                    r = np.empty(n_delta, dtype=np.int64)
                    ub = np.unique(bins_sorted_d)
                    touched_bins = len(ub)
                    for b in ub:
                        ds = np.searchsorted(bins_sorted_d, b, side="left")
                        de = np.searchsorted(bins_sorted_d, b, side="right")
                        rs = np.searchsorted(old_bins, b, side="left")
                        re_ = np.searchsorted(old_bins, b, side="right")
                        r[ds:de] = rs + np.searchsorted(
                            old_sec[rs:re_], sec_sorted_d[ds:de],
                            side="right")
                else:
                    r = np.searchsorted(old_sec, sec_sorted_d,
                                        side="right").astype(np.int64)

            # merged positions: resident i shifts by the count of delta rows
            # ranked at-or-before it; delta j lands right after its rank
            shift = np.searchsorted(r, np.arange(n_old, dtype=np.int64),
                                    side="right")
            pos_res = np.arange(n_old, dtype=np.int64) + shift
            pos_del = r + np.arange(n_delta, dtype=np.int64)

            if keys_d is not None:
                if z_d is not None:
                    self._z = np.concatenate([np.asarray(old._z), sec_d])
                else:
                    self._xz = np.concatenate([np.asarray(old._xz), sec_d])
                sorted_sec = np.empty(n_new, dtype=old_sec.dtype)
                sorted_sec[pos_res] = old_sec
                sorted_sec[pos_del] = sec_sorted_d
                setattr(self, "_sorted_z" if z_d is not None else
                        "_sorted_xz", sorted_sec)
                if bins_d is not None:
                    self._bins = np.concatenate(
                        [np.asarray(old._bins), bins_d])
                    sorted_bins = np.empty(n_new, dtype=old_bins.dtype)
                    sorted_bins[pos_res] = old_bins
                    sorted_bins[pos_del] = bins_sorted_d
                    self._sorted_bins = sorted_bins

            # permutation: merged on device when the resident perm is
            # device-resident (avoids an O(n_old) download), else on host
            perm_pair = None
            if old._perm_cache is None and old._dev_perm is not None:
                perm_pair = (old._dev_perm,
                             (n_old + p_d).astype(np.int32))
            else:
                new_perm = np.empty(n_new, dtype=np.int64)
                new_perm[pos_res] = old.perm
                new_perm[pos_del] = n_old + p_d
                self._perm_cache = new_perm

            # dictionary columns whose vocab grew under the union-vocab
            # concat: resident device codes are invalid — rebuild those
            # columns from the merged full plane (everything else merges
            # with delta-sized uploads only)
            merged_vocabs = {
                name: col.vocab
                for name, col in merged_table.columns.items()
                if isinstance(col, StringColumn)}
            stale = set()
            full_codes: Dict[str, np.ndarray] = {}
            for name in old.device.columns:
                if name in merged_vocabs \
                        and old.vocabs.get(name) != merged_vocabs[name]:
                    stale.add(name)
                    full_codes[name] = np.asarray(
                        merged_table.columns[name].codes, dtype=np.int32)
            old_vis = old.table.visibility
            new_vis = merged_table.visibility
            if new_vis is not None and (
                    "__vis__" not in old.device.columns
                    or old_vis is None or old_vis.vocab != new_vis.vocab):
                stale.add("__vis__")
                full_codes["__vis__"] = np.asarray(new_vis.codes,
                                                   dtype=np.int32)

            # device columns live in SORTED order — gather the delta planes
            # into delta-sorted order so pos_del scatters rows against the
            # right keys
            delta_planes = {k: np.asarray(v)[p_d]
                            for k, v in host_planes(delta_table,
                                                    old.period).items()}
            self.device, new_dev_perm = DeviceTable.merge_scatter(
                old.device, delta_planes, r, stale=stale,
                full_codes=full_codes, perm_pair=perm_pair,
                host_perm=self._perm_cache)
            if new_dev_perm is not None:
                self._dev_perm = new_dev_perm

            self.kernels = ScanKernels(self.device.columns)
            self.vocabs = merged_vocabs
            self.build_stages = {
                "merge_s": round(_time.perf_counter() - t0, 3),
                "merge_rows": int(n_delta),
                "merge_fraction": round(n_delta / max(1, n_old), 4),
                "merge_touched_bins": int(touched_bins),
                "merge_stale_cols": sorted(stale),
            }
        return self

    @classmethod
    def supports(cls, sft) -> bool:
        raise NotImplementedError

    # planning ---------------------------------------------------------------

    def plan(self, f: ir.Filter) -> Optional[IndexScanPlan]:
        ext = extract_bboxes(f, self.geom) if self.geom else extract.Extraction(
            (extract.WHOLE_WORLD,), False)
        iv = extract_intervals(f, self.dtg) if self.dtg else None

        if len(ext.boxes) == 0 or (iv is not None and len(iv.intervals) == 0):
            return IndexScanPlan(self, "none", empty=True, full_filter=f, cost=0.0)

        residual = _strip_handled(f, self.geom, self.dtg, self.points)

        boxes_loose = None
        kind = "none"
        if not ext.unconstrained:
            kind = "point_boxes" if self.points else "bbox_overlap"
            boxes_loose = pad_boxes(_boxes_fp62(ext.boxes))

        windows = None
        if iv is not None and not iv.unconstrained:
            w = np.empty((len(iv.intervals), 4), dtype=np.int32)
            i32 = (1 << 31) - 1  # open-ended intervals overflow the bin i32
            for i, (lo, hi) in enumerate(iv.intervals):
                blo, olo = time_to_binned_time(lo, self.period)
                bhi, ohi = time_to_binned_time(hi, self.period)
                w[i] = (max(-i32, int(blo)), int(olo),
                        min(i32, int(bhi)), int(ohi))
            windows = pad_windows(w)

        avail = set(self.device.columns)
        dev_res, host_res = split_residual(residual, self.sft, self.vocabs,
                                           avail)
        compiled = compile_residual(dev_res, self.sft, self.vocabs, avail) \
            if dev_res else None

        cost = self._cost(ext, iv)
        return IndexScanPlan(
            index=self,
            primary_kind=kind,
            boxes_loose=boxes_loose,
            windows=windows,
            residual_device=compiled,
            residual_host=host_res,
            full_filter=f,
            cost=cost,
            explain={"index": self.name, "boxes": ext.boxes,
                     "intervals": None if iv is None else iv.intervals,
                     "residual_device": dev_res, "residual_host": host_res},
        )

    def _cost(self, ext, iv) -> float:
        """Heuristic strategy cost (≙ StrategyDecider index heuristics —
        lower is better; spatio-temporal beats spatial beats full scan)."""
        spatial = not ext.unconstrained
        temporal = iv is not None and not iv.unconstrained
        if self.temporal and spatial and temporal:
            return 1.0
        if spatial:
            return 2.0 if not self.temporal else 2.5
        if temporal and self.temporal:
            return 3.0
        return 10.0  # full scan

    # certified segment predicates ------------------------------------------

    def ensure_segment_columns(self) -> bool:
        """Upload per-feature segment endpoints (sx1/sy1/sx2/sy2 f32) when
        every feature is a single-segment LineString — enabling the device
        certainty-band intersects refine (scan.intersects_band_blocks).
        Lazy + cached; False when the layer shape doesn't qualify."""
        cached = getattr(self, "_seg_cols_ok", None)
        if cached is not None:
            return cached
        ok = False
        garr = self.table.geometry()
        if not garr.is_points and len(garr):
            from geomesa_tpu.features import geometry as geo
            counts = np.diff(garr.ring_offsets)
            if (np.all(garr.type_codes == geo.LINESTRING)
                    and len(counts) == len(garr) and np.all(counts == 2)):
                import jax.numpy as jnp
                segs = garr.coords.reshape(len(garr), 4)[self.perm]
                for i, name in enumerate(("sx1", "sy1", "sx2", "sy2")):
                    self.device.columns[name] = jnp.asarray(
                        segs[:, i].astype(np.float32))
                ok = True
        self._seg_cols_ok = ok
        return ok

    # range pruning ---------------------------------------------------------

    def candidate_blocks(self, plan: IndexScanPlan):
        """Sorted unique gather-block ids covering every possibly-matching
        row, or None when pruning doesn't apply or wouldn't pay (the device
        re-applies the full exact mask to gathered blocks, so this only ever
        needs to be a superset). ≙ the reference's ≤2000-range scan plans
        (Z3IndexKeySpace.getRanges:162-189); the decision threshold mirrors
        full-table-scan avoidance (QueryProperties.BlockFullTableScans)."""
        from geomesa_tpu.index import prune as _p

        if plan.empty or plan.boxes_loose is None:
            return None  # no spatial constraint → nothing to cover
        boxes = plan.explain.get("boxes")
        if not boxes or len(boxes) > 16:
            return None
        n = len(self.table)
        if n < 4 * _p.BLOCK_SIZE:
            return None  # tiny tables: full mask is a single fused pass
        # plan.windows is None iff the temporal extraction was unconstrained —
        # the explain intervals then hold the open-ended sentinel, which must
        # read as "no temporal constraint", not as a 146-million-bin interval
        intervals = plan.explain.get("intervals") if plan.windows is not None else None
        slices = self._row_slices(list(boxes), intervals)
        if slices is None:
            return None
        total = int((slices[:, 1] - slices[:, 0]).sum()) if len(slices) else 0
        if total > _p.PRUNE_MAX_FRACTION * n:
            return None
        blocks = _p.slices_to_blocks(slices, n)
        if blocks is not None and len(blocks) * _p.BLOCK_SIZE > _p.PRUNE_MAX_FRACTION * n:
            return None
        plan.explain.update(_p.candidate_stats(slices, blocks, n))
        if blocks is None:
            # provably empty candidate set — still exact (superset of nothing)
            blocks = np.empty(0, dtype=np.int32)
        return blocks

    def _row_slices(self, boxes, intervals) -> Optional[np.ndarray]:
        """Candidate [lo, hi) row slices in this index's sorted order (a
        superset of matches), or None when unsupported."""
        return None

    def _bin_segments(self):
        from geomesa_tpu.index.prune import BinSegments
        if getattr(self, "_bin_segs", None) is None:
            self._join_prefetch()
        if getattr(self, "_bin_segs", None) is None:
            self._bin_segs = BinSegments(self.sorted_bins)
        return self._bin_segs

    def _sorted_plane(self, attr: str, src: np.ndarray) -> np.ndarray:
        """Sorted host key plane, preferring the build-time background
        prefetch result over a synchronous (100s-of-ms at 10M+) gather."""
        cached = getattr(self, attr, None)
        if cached is None:
            self._join_prefetch()
            cached = getattr(self, attr, None)
        if cached is None:
            cached = src[self.perm]
            setattr(self, attr, cached)
        return cached

    def _binned_row_slices(self, boxes, intervals, sorted_keys,
                           cover_fn) -> Optional[np.ndarray]:
        """Shared epoch-major pruning: per-bin segments × per-window covers
        (covers dedup by in-bin window, so a multi-bin interval costs at most
        three distinct covers: head, whole-period, tail)."""
        from geomesa_tpu.index import prune as _p
        from geomesa_tpu.curves.binnedtime import max_offset

        segs = self._bin_segments()
        mo = max_offset(self.period) - 1
        if intervals:
            bw = _p.bin_windows(intervals, self.period)
            if bw is None:
                return None
        else:
            bins = segs.all_bins()
            if len(bins) > _p.MAX_BINS:
                return None
            bw = [(int(b), (0, mo)) for b in bins]
        covers = {}
        out = []
        for b, w in bw:
            lo, hi = segs.segment(b)
            if lo >= hi:
                continue
            if w not in covers:
                covers[w] = cover_fn(boxes, w)
            out.append(_p.ranges_to_slices(sorted_keys, covers[w], lo=lo, hi=hi))
        return np.concatenate(out) if out else np.empty((0, 2), dtype=np.int64)

    # explain ---------------------------------------------------------------

    def key_ranges(self, plan: IndexScanPlan, max_ranges: int = 2000):
        """Reference-style z/xz range decomposition for this plan (explain/
        pruning; not needed for the full-scan execution path)."""
        raise NotImplementedError


class Z3Index(BaseSpatialIndex):
    """Point + time: epoch-major (bin, z3) order (≙ Z3IndexKeySpace.scala:34,
    row layout [shard][epoch:2][z:8])."""

    name = "z3"
    temporal = True
    points = True

    @classmethod
    def supports(cls, sft) -> bool:
        g = sft.geometry_attribute
        return g is not None and g.type_name == "Point" and sft.dtg_attribute is not None

    def _sort_keys(self) -> List[np.ndarray]:
        garr = self.table.geometry()
        x, y = garr.point_xy()
        ms = np.asarray(self.table.columns[self.dtg], dtype=np.int64)
        bins, offs = time_to_binned_time(ms, self.period)
        sfc = Z3SFC.apply(self.period)
        self._sfc = sfc
        self._z = sfc.index(x, y, np.minimum(offs, int(sfc.time.max)),
                            lenient=True)
        self._bins = bins
        return [np.asarray(bins, dtype=np.int32)] + _split63(self._z)

    def _build_native(self) -> bool:
        from geomesa_tpu import native
        garr = self.table.geometry()
        if not (garr.is_points and native.available()):
            return False
        x, y = garr.point_xy()
        ms = np.asarray(self.table.columns[self.dtg], dtype=np.int64)
        import time as _time

        self._sfc = Z3SFC.apply(self.period)
        extra = host_planes(self.table, self.period,
                            skip_geom=True, skip_dtg=True)
        streamed = self._stream_build(
            lambda a, b: native.z3_encode(x[a:b], y[a:b], ms[a:b],
                                          self.period.value),
            ["bin16", "zhi", "zlo"], len(x), extra)
        if streamed is not None:
            return streamed
        t0 = _time.perf_counter()
        enc = native.z3_encode(x, y, ms, self.period.value)
        if enc is None:  # calendar periods stay on the numpy path
            return False
        self.build_stages = {"encode_s": round(_time.perf_counter() - t0, 2)}
        self._z = enc["z"]
        self._bins = enc["bin16"]
        self._finish_native(enc, ["bin16", "zhi", "zlo"], extra)
        return True

    @property
    def sorted_z(self) -> np.ndarray:
        return self._sorted_plane("_sorted_z", self._z)

    @property
    def sorted_bins(self) -> np.ndarray:
        return self._sorted_plane("_sorted_bins", self._bins)

    def key_ranges(self, plan, max_ranges: int = 2000):
        ext = extract_bboxes(plan.full_filter, self.geom)
        iv = extract_intervals(plan.full_filter, self.dtg)
        ranges = []
        for lo, hi in iv.intervals[:8] if not iv.unconstrained else []:
            blo, olo = time_to_binned_time(lo, self.period)
            bhi, ohi = time_to_binned_time(hi, self.period)
            for b in range(int(blo), int(bhi) + 1):
                t0 = int(olo) if b == int(blo) else 0
                t1 = int(ohi) if b == int(bhi) else max_offset(self.period) - 1
                rs = self._sfc.ranges(list(ext.boxes), [(t0, t1)], max_ranges=max_ranges)
                ranges.append((b, rs))
        return ranges

    def _row_slices(self, boxes, intervals):
        from geomesa_tpu.index.prune import MAX_RANGES
        return self._binned_row_slices(
            boxes, intervals, self.sorted_z,
            lambda bx, w: self._sfc.ranges_arrays(bx, [w],
                                                  max_ranges=MAX_RANGES))


class Z2Index(BaseSpatialIndex):
    """Point, no time: z2 order (≙ Z2IndexKeySpace.scala:29)."""

    name = "z2"
    temporal = False
    points = True

    @classmethod
    def supports(cls, sft) -> bool:
        g = sft.geometry_attribute
        return g is not None and g.type_name == "Point"

    def _sort_keys(self) -> List[np.ndarray]:
        x, y = self.table.geometry().point_xy()
        self._z = Z2SFC().index(x, y, lenient=True)
        return _split63(self._z)

    def _build_native(self) -> bool:
        from geomesa_tpu import native
        garr = self.table.geometry()
        if not (garr.is_points and native.available()):
            return False
        x, y = garr.point_xy()
        extra = host_planes(self.table, self.period, skip_geom=True)
        streamed = self._stream_build(
            lambda a, b: native.z2_encode(x[a:b], y[a:b]),
            ["zhi", "zlo"], len(x), extra)
        if streamed is not None:
            return streamed
        enc = native.z2_encode(x, y)
        if enc is None:
            return False
        self._z = enc["z"]
        self._finish_native(enc, ["zhi", "zlo"], extra)
        return True

    @property
    def sorted_z(self) -> np.ndarray:
        return self._sorted_plane("_sorted_z", self._z)

    def _row_slices(self, boxes, intervals):
        from geomesa_tpu.index.prune import MAX_RANGES, ranges_to_slices
        rs = Z2SFC().ranges_arrays(boxes, max_ranges=MAX_RANGES)
        return ranges_to_slices(self.sorted_z, rs)


class XZ3Index(BaseSpatialIndex):
    """Extent + time: (bin, xz3) order (≙ XZ3IndexKeySpace.scala:33)."""

    name = "xz3"
    temporal = True
    points = False

    @classmethod
    def supports(cls, sft) -> bool:
        g = sft.geometry_attribute
        return g is not None and g.type_name != "Point" and sft.dtg_attribute is not None

    def _sort_keys(self) -> List[np.ndarray]:
        bb = self.table.geometry().bboxes()
        ms = np.asarray(self.table.columns[self.dtg], dtype=np.int64)
        bins, offs = time_to_binned_time(ms, self.period)
        sfc = XZ3SFC.apply(self.sft.xz_precision, self.period)
        mins = np.stack([bb[:, 0], bb[:, 1], offs.astype(np.float64)], axis=1)
        maxs = np.stack([bb[:, 2], bb[:, 3], offs.astype(np.float64)], axis=1)
        self._xz = sfc.index(mins, maxs, lenient=True)
        self._bins = bins
        return [np.asarray(bins, dtype=np.int32)] + _split63(self._xz)

    @property
    def sorted_xz(self) -> np.ndarray:
        return self._sorted_plane("_sorted_xz", self._xz)

    @property
    def sorted_bins(self) -> np.ndarray:
        return self._sorted_plane("_sorted_bins", self._bins)

    def _row_slices(self, boxes, intervals):
        from geomesa_tpu.index.prune import MAX_RANGES
        sfc = XZ3SFC.apply(self.sft.xz_precision, self.period)

        def cover(bx, w):
            qs = [(xmin, ymin, float(w[0]), xmax, ymax, float(w[1]))
                  for xmin, ymin, xmax, ymax in bx]
            return sfc.ranges(qs, max_ranges=MAX_RANGES)

        return self._binned_row_slices(boxes, intervals, self.sorted_xz, cover)


class XZ2Index(BaseSpatialIndex):
    """Extent, no time: xz2 order (≙ XZ2IndexKeySpace.scala:28)."""

    name = "xz2"
    temporal = False
    points = False

    @classmethod
    def supports(cls, sft) -> bool:
        g = sft.geometry_attribute
        return g is not None and g.type_name != "Point"

    def _sort_keys(self) -> List[np.ndarray]:
        bb = self.table.geometry().bboxes()
        sfc = XZ2SFC.apply(self.sft.xz_precision)
        self._xz = sfc.index(bb[:, [0, 1]], bb[:, [2, 3]], lenient=True)
        return _split63(self._xz)

    @property
    def sorted_xz(self) -> np.ndarray:
        return self._sorted_plane("_sorted_xz", self._xz)

    def _row_slices(self, boxes, intervals):
        from geomesa_tpu.index.prune import MAX_RANGES, ranges_to_slices
        sfc = XZ2SFC.apply(self.sft.xz_precision)
        rs = sfc.ranges_bbox(boxes, max_ranges=MAX_RANGES)
        return ranges_to_slices(self.sorted_xz, rs)


class S2Index(BaseSpatialIndex):
    """Point, no time, S2 (Hilbert-on-cube) order — opt-in via
    ``geomesa.indices=s2`` (≙ S2IndexKeySpace.scala:34; the reference's S2
    indexes are likewise configured, not default)."""

    name = "s2"
    temporal = False
    points = True
    # measured cover slop vs true rows (curves/s2.py _cell_rect): the cost
    # model prices S2 plans above an equally-selective Z cover
    cover_slop = 1.1

    @classmethod
    def supports(cls, sft) -> bool:
        g = sft.geometry_attribute
        names = sft.configured_indices
        return (names is not None and "s2" in names
                and g is not None and g.type_name == "Point")

    def _sort_keys(self) -> List[np.ndarray]:
        from geomesa_tpu.curves.s2 import S2SFC
        x, y = self.table.geometry().point_xy()
        self._z = S2SFC.apply().index(x, y, lenient=True)
        return _split63(self._z)

    @property
    def sorted_z(self) -> np.ndarray:
        return self._sorted_plane("_sorted_z", self._z)

    def _row_slices(self, boxes, intervals):
        from geomesa_tpu.curves.s2 import S2SFC
        from geomesa_tpu.index.prune import MAX_RANGES, ranges_to_slices
        rs = S2SFC.apply().ranges(boxes, max_ranges=MAX_RANGES)
        return ranges_to_slices(self.sorted_z, rs)


class S3Index(BaseSpatialIndex):
    """Point + time, epoch-major (bin, s2) order — opt-in via
    ``geomesa.indices=s3`` (≙ S3IndexKeySpace.scala:36 / S3Filter: the S2
    cell id carries no time bits, so temporal pruning lands at bin
    granularity exactly as in the reference's [epoch][s2] layout)."""

    name = "s3"
    temporal = True
    points = True
    cover_slop = 1.1  # see S2Index

    @classmethod
    def supports(cls, sft) -> bool:
        g = sft.geometry_attribute
        names = sft.configured_indices
        return (names is not None and "s3" in names and g is not None
                and g.type_name == "Point" and sft.dtg_attribute is not None)

    def _sort_keys(self) -> List[np.ndarray]:
        from geomesa_tpu.curves.s2 import S2SFC
        x, y = self.table.geometry().point_xy()
        ms = np.asarray(self.table.columns[self.dtg], dtype=np.int64)
        bins, _ = time_to_binned_time(ms, self.period)
        self._z = S2SFC.apply().index(x, y, lenient=True)
        self._bins = bins
        return [np.asarray(bins, dtype=np.int32)] + _split63(self._z)

    @property
    def sorted_z(self) -> np.ndarray:
        return self._sorted_plane("_sorted_z", self._z)

    @property
    def sorted_bins(self) -> np.ndarray:
        return self._sorted_plane("_sorted_bins", self._bins)

    def _row_slices(self, boxes, intervals):
        from geomesa_tpu.curves.s2 import S2SFC
        from geomesa_tpu.index.prune import MAX_RANGES
        sfc = S2SFC.apply()
        cover = {}

        def cover_fn(bx, w):  # no time dim in the s2 key: one shared cover
            if "c" not in cover:
                cover["c"] = sfc.ranges(bx, max_ranges=MAX_RANGES)
            return cover["c"]

        return self._binned_row_slices(boxes, intervals, self.sorted_z,
                                       cover_fn)


class FullScanIndex(BaseSpatialIndex):
    """Natural-order fallback for schemas with no usable spatial index or
    queries no index serves (≙ the reference's full-table-scan strategy,
    guarded there by QueryProperties.BlockFullTableScans)."""

    name = "full"
    temporal = False
    points = True

    @classmethod
    def supports(cls, sft) -> bool:
        return True

    def _sort_keys(self) -> Optional[List[np.ndarray]]:
        return None  # natural table order

    def plan(self, f: ir.Filter) -> Optional[IndexScanPlan]:
        avail = set(self.device.columns)
        dev_res, host_res = split_residual(
            f if not isinstance(f, (ir.Include,)) else None, self.sft,
            self.vocabs, avail)
        compiled = compile_residual(dev_res, self.sft, self.vocabs, avail) \
            if dev_res else None
        return IndexScanPlan(
            index=self, primary_kind="none",
            residual_device=compiled, residual_host=host_res, full_filter=f,
            cost=100.0, explain={"index": self.name, "residual_host": host_res},
        )


INDEX_CLASSES = [S3Index, S2Index, Z3Index, XZ3Index, Z2Index, XZ2Index]
