"""Spatial index implementations: Z2 / Z3 / XZ2 / XZ3.

≙ reference index.index.{z2,z3} key spaces (Z3IndexKeySpace.scala:34 etc.).
Each index owns a device-resident projection of the table sorted in its key
order (epoch-major for the temporal variants — the epoch bin is the row-key
prefix exactly as in the reference's ``[shard][epoch:2][z:8]`` layout), plus
host-side sorted key arrays for range pruning, and produces IndexScanPlans:

  - spatial constraint → padded int31 boxes, loose (cell cover) + strict
    (cell interior) — the contained/overlapping-range distinction
  - temporal constraint → exact (bin, offset) windows (Z3Filter.timeInBounds)
  - leftover predicates → device residual (compiled) + host residual

The scan itself is a full-table fused mask (bandwidth-bound, fast on TPU);
the sorted layout + host key arrays enable block-range pruning (searchsorted
over the reference-style z-range cover) which the planner can enable for
low-selectivity queries.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from geomesa_tpu.curves.binnedtime import TimePeriod, max_offset, time_to_binned_time
from geomesa_tpu.curves.normalize import NormalizedLat, NormalizedLon
from geomesa_tpu.curves.sfc import Z2SFC, Z3SFC
from geomesa_tpu.curves.xz import XZ2SFC, XZ3SFC
from geomesa_tpu.curves import zorder
from geomesa_tpu.features.table import FeatureTable, StringColumn
from geomesa_tpu.filter import extract, ir
from geomesa_tpu.filter.extract import extract_bboxes, extract_intervals
from geomesa_tpu.index.api import IndexScanPlan
from geomesa_tpu.index.device import DeviceTable, fp62_lat, fp62_lon
from geomesa_tpu.index.scan import ScanKernels, pad_boxes, pad_windows, split_residual, compile_residual


def _strip_handled(f: ir.Filter, geom: Optional[str], dtg: Optional[str],
                   points: bool) -> Optional[ir.Filter]:
    """Residual after removing predicates the primary boxes/windows enforce
    exactly.

    A spatial node drops when its box extraction IS the predicate: BBox
    (envelope-overlap semantics, exact for points and extents alike via the
    fp62 envelope planes) and, for point layers only, exact-extracting
    Intersects (point/rectangle literals). Temporal nodes on ``dtg`` always
    drop (windows are exact). OR-rooted filters keep the whole filter as
    residual (the boxes/windows become a superset prefilter) — the
    conservative analogue of the reference's DNF expansion fallback
    (FilterSplitter.scala:61-103).
    """
    if isinstance(f, ir.Or):
        return f
    children = f.children if isinstance(f, ir.And) else (f,)
    rest: List[ir.Filter] = []
    for c in children:
        if isinstance(c, (ir.BBox, ir.Intersects, ir.Contains, ir.Within, ir.Dwithin)) \
                and (geom is None or c.attr == geom):
            if isinstance(c, ir.BBox):
                continue  # envelope semantics: primary boxes are exact
            if points and extract_bboxes(c, geom).exact:
                continue  # point-in-rectangle: primary boxes are exact
            rest.append(c)
        elif isinstance(c, ir.During) and c.attr == dtg:
            continue  # exact via windows
        elif isinstance(c, ir.Cmp) and c.attr == dtg and isinstance(c.value, (int, np.integer)):
            continue  # exact via windows
        else:
            rest.append(c)
    return ir.and_filters(rest) if rest else None


def _boxes_fp62(boxes) -> np.ndarray:
    """User-space boxes → (B, 8) int32 fp62 query planes:
    [qxlo_hi, qxlo_lo, qxhi_hi, qxhi_lo, qylo_hi, qylo_lo, qyhi_hi, qyhi_lo].
    Device comparisons against these reproduce f64 bounds exactly (device.fp62)."""
    out = np.empty((len(boxes), 8), dtype=np.int32)
    for i, (xmin, ymin, xmax, ymax) in enumerate(boxes):
        xlo = fp62_lon(xmin)
        xhi = fp62_lon(xmax)
        ylo = fp62_lat(ymin)
        yhi = fp62_lat(ymax)
        out[i] = (xlo[0], xlo[1], xhi[0], xhi[1], ylo[0], ylo[1], yhi[0], yhi[1])
    return out


class BaseSpatialIndex:
    """Shared machinery: device table, kernels, plan construction."""

    name: str = "base"
    temporal: bool = False
    points: bool = True

    def __init__(self, sft, table: FeatureTable):
        self.sft = sft
        self.table = table
        self.geom = sft.geometry_attribute.name if sft.geometry_attribute else None
        dtg = sft.dtg_attribute
        self.dtg = dtg.name if dtg else None
        self.period = TimePeriod.parse(sft.z3_interval) if self.dtg else None
        self.perm = self._sort_permutation()
        self.device = DeviceTable.build(table, self.perm, self.period)
        self.kernels = ScanKernels(self.device.columns)
        self.vocabs = {
            name: col.vocab for name, col in table.columns.items()
            if isinstance(col, StringColumn)
        }

    # subclasses supply the key sort ----------------------------------------

    def _sort_permutation(self) -> np.ndarray:
        raise NotImplementedError

    @classmethod
    def supports(cls, sft) -> bool:
        raise NotImplementedError

    # planning ---------------------------------------------------------------

    def plan(self, f: ir.Filter) -> Optional[IndexScanPlan]:
        ext = extract_bboxes(f, self.geom) if self.geom else extract.Extraction(
            (extract.WHOLE_WORLD,), False)
        iv = extract_intervals(f, self.dtg) if self.dtg else None

        if len(ext.boxes) == 0 or (iv is not None and len(iv.intervals) == 0):
            return IndexScanPlan(self, "none", empty=True, full_filter=f, cost=0.0)

        residual = _strip_handled(f, self.geom, self.dtg, self.points)

        boxes_loose = None
        kind = "none"
        if not ext.unconstrained:
            kind = "point_boxes" if self.points else "bbox_overlap"
            boxes_loose = pad_boxes(_boxes_fp62(ext.boxes))

        windows = None
        if iv is not None and not iv.unconstrained:
            w = np.empty((len(iv.intervals), 4), dtype=np.int32)
            i32 = (1 << 31) - 1  # open-ended intervals overflow the bin i32
            for i, (lo, hi) in enumerate(iv.intervals):
                blo, olo = time_to_binned_time(lo, self.period)
                bhi, ohi = time_to_binned_time(hi, self.period)
                w[i] = (max(-i32, int(blo)), int(olo),
                        min(i32, int(bhi)), int(ohi))
            windows = pad_windows(w)

        dev_res, host_res = split_residual(residual, self.sft, self.vocabs)
        compiled = compile_residual(dev_res, self.sft, self.vocabs) if dev_res else None

        cost = self._cost(ext, iv)
        return IndexScanPlan(
            index=self,
            primary_kind=kind,
            boxes_loose=boxes_loose,
            windows=windows,
            residual_device=compiled,
            residual_host=host_res,
            full_filter=f,
            cost=cost,
            explain={"index": self.name, "boxes": ext.boxes,
                     "intervals": None if iv is None else iv.intervals,
                     "residual_device": dev_res, "residual_host": host_res},
        )

    def _cost(self, ext, iv) -> float:
        """Heuristic strategy cost (≙ StrategyDecider index heuristics —
        lower is better; spatio-temporal beats spatial beats full scan)."""
        spatial = not ext.unconstrained
        temporal = iv is not None and not iv.unconstrained
        if self.temporal and spatial and temporal:
            return 1.0
        if spatial:
            return 2.0 if not self.temporal else 2.5
        if temporal and self.temporal:
            return 3.0
        return 10.0  # full scan

    # explain ---------------------------------------------------------------

    def key_ranges(self, plan: IndexScanPlan, max_ranges: int = 2000):
        """Reference-style z/xz range decomposition for this plan (explain/
        pruning; not needed for the full-scan execution path)."""
        raise NotImplementedError


class Z3Index(BaseSpatialIndex):
    """Point + time: epoch-major (bin, z3) order (≙ Z3IndexKeySpace.scala:34,
    row layout [shard][epoch:2][z:8])."""

    name = "z3"
    temporal = True
    points = True

    @classmethod
    def supports(cls, sft) -> bool:
        g = sft.geometry_attribute
        return g is not None and g.type_name == "Point" and sft.dtg_attribute is not None

    def _sort_permutation(self) -> np.ndarray:
        garr = self.table.geometry()
        x, y = garr.point_xy()
        ms = np.asarray(self.table.columns[self.dtg], dtype=np.int64)
        bins, offs = time_to_binned_time(ms, self.period)
        sfc = Z3SFC.apply(self.period)
        z = sfc.index(x, y, np.minimum(offs, int(sfc.time.max)), lenient=True)
        self._host_bins = None  # set after sort below
        perm = np.lexsort((z, bins))
        self._sorted_bins = bins[perm]
        self._sorted_z = z[perm]
        self._sfc = sfc
        return perm

    def key_ranges(self, plan, max_ranges: int = 2000):
        ext = extract_bboxes(plan.full_filter, self.geom)
        iv = extract_intervals(plan.full_filter, self.dtg)
        ranges = []
        for lo, hi in iv.intervals[:8] if not iv.unconstrained else []:
            blo, olo = time_to_binned_time(lo, self.period)
            bhi, ohi = time_to_binned_time(hi, self.period)
            for b in range(int(blo), int(bhi) + 1):
                t0 = int(olo) if b == int(blo) else 0
                t1 = int(ohi) if b == int(bhi) else max_offset(self.period) - 1
                rs = self._sfc.ranges(list(ext.boxes), [(t0, t1)], max_ranges=max_ranges)
                ranges.append((b, rs))
        return ranges


class Z2Index(BaseSpatialIndex):
    """Point, no time: z2 order (≙ Z2IndexKeySpace.scala:29)."""

    name = "z2"
    temporal = False
    points = True

    @classmethod
    def supports(cls, sft) -> bool:
        g = sft.geometry_attribute
        return g is not None and g.type_name == "Point"

    def _sort_permutation(self) -> np.ndarray:
        x, y = self.table.geometry().point_xy()
        z = Z2SFC().index(x, y, lenient=True)
        self._sorted_z = np.sort(z)
        return np.argsort(z, kind="stable")


class XZ3Index(BaseSpatialIndex):
    """Extent + time: (bin, xz3) order (≙ XZ3IndexKeySpace.scala:33)."""

    name = "xz3"
    temporal = True
    points = False

    @classmethod
    def supports(cls, sft) -> bool:
        g = sft.geometry_attribute
        return g is not None and g.type_name != "Point" and sft.dtg_attribute is not None

    def _sort_permutation(self) -> np.ndarray:
        bb = self.table.geometry().bboxes()
        ms = np.asarray(self.table.columns[self.dtg], dtype=np.int64)
        bins, offs = time_to_binned_time(ms, self.period)
        sfc = XZ3SFC.apply(self.sft.xz_precision, self.period)
        mins = np.stack([bb[:, 0], bb[:, 1], offs.astype(np.float64)], axis=1)
        maxs = np.stack([bb[:, 2], bb[:, 3], offs.astype(np.float64)], axis=1)
        xz = sfc.index(mins, maxs, lenient=True)
        perm = np.lexsort((xz, bins))
        self._sorted_bins = bins[perm]
        self._sorted_xz = xz[perm]
        return perm


class XZ2Index(BaseSpatialIndex):
    """Extent, no time: xz2 order (≙ XZ2IndexKeySpace.scala:28)."""

    name = "xz2"
    temporal = False
    points = False

    @classmethod
    def supports(cls, sft) -> bool:
        g = sft.geometry_attribute
        return g is not None and g.type_name != "Point"

    def _sort_permutation(self) -> np.ndarray:
        bb = self.table.geometry().bboxes()
        sfc = XZ2SFC.apply(self.sft.xz_precision)
        xz = sfc.index(bb[:, [0, 1]], bb[:, [2, 3]], lenient=True)
        self._sorted_xz = np.sort(xz)
        return np.argsort(xz, kind="stable")


class FullScanIndex(BaseSpatialIndex):
    """Natural-order fallback for schemas with no usable spatial index or
    queries no index serves (≙ the reference's full-table-scan strategy,
    guarded there by QueryProperties.BlockFullTableScans)."""

    name = "full"
    temporal = False
    points = True

    @classmethod
    def supports(cls, sft) -> bool:
        return True

    def _sort_permutation(self) -> np.ndarray:
        return np.arange(len(self.table), dtype=np.int64)

    def plan(self, f: ir.Filter) -> Optional[IndexScanPlan]:
        dev_res, host_res = split_residual(
            f if not isinstance(f, (ir.Include,)) else None, self.sft, self.vocabs)
        compiled = compile_residual(dev_res, self.sft, self.vocabs) if dev_res else None
        return IndexScanPlan(
            index=self, primary_kind="none",
            residual_device=compiled, residual_host=host_res, full_filter=f,
            cost=100.0, explain={"index": self.name, "residual_host": host_res},
        )


INDEX_CLASSES = [Z3Index, XZ3Index, Z2Index, XZ2Index]
