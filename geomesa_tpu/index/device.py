"""DeviceTable: the HBM-resident columnar projection the scan kernels read.

≙ the data a GeoMesa region/tablet server holds for one index table: rows in
index-key order with the serialized values (SURVEY.md §3.2 step 4). Here the
"rows" are structure-of-arrays jnp buffers in index-sorted order:

  - ``xi``/``yi``  int32 31-bit normalized coords (Z2SFC resolution — exact to
                   ~2 cm; the canonical device coordinates for box tests)
  - ``xf``/``yf``  float32 raw coords (aggregations, joins, density)
  - ``bin``/``off`` int32 exact binned time (period bin + integer offset in
                   period units — ms/s/min, exactly representable)
  - bbox columns (extent geometries): f32 xmin/ymin/xmax/ymax
  - attribute columns: numeric as int32/f32; strings as dictionary codes;
                   dates additionally as (bin, off) when they are the primary
                   temporal axis

Only numeric-representable projections live on device; exact f64 coordinates
and ragged geometry buffers stay host-side for refinement (the reference's
full-filter path).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional

import jax.numpy as jnp
import numpy as np

from geomesa_tpu.curves.binnedtime import TimePeriod, time_to_binned_time
from geomesa_tpu.curves.normalize import NormalizedLat, NormalizedLon
from geomesa_tpu.features.geometry import GeometryArray
from geomesa_tpu.features.table import FeatureTable, StringColumn

LON31 = NormalizedLon(31)
LAT31 = NormalizedLat(31)


@dataclass
class DeviceTable:
    """Device-resident columns for one index, in index-sorted row order."""

    n: int
    columns: Dict[str, jnp.ndarray] = field(default_factory=dict)

    def __getitem__(self, name: str) -> jnp.ndarray:
        return self.columns[name]

    def __contains__(self, name: str) -> bool:
        return name in self.columns

    @classmethod
    def build(
        cls,
        table: FeatureTable,
        perm: np.ndarray,
        period: Optional[TimePeriod] = None,
    ) -> "DeviceTable":
        """Project ``table`` rows (reordered by ``perm``) onto the device.

        period: when set, the default dtg column is decomposed into exact
        (bin, off) int32 pairs for temporal predicates.
        """
        n = len(perm)
        cols: Dict[str, jnp.ndarray] = {}

        geom_attr = table.sft.geometry_attribute
        if geom_attr is not None:
            garr: GeometryArray = table.columns[geom_attr.name]
            if garr.is_points:
                x, y = garr.point_xy()
                x, y = x[perm], y[perm]
                cols["xi"] = jnp.asarray(LON31.normalize(x), dtype=jnp.int32)
                cols["yi"] = jnp.asarray(LAT31.normalize(y), dtype=jnp.int32)
                cols["xf"] = jnp.asarray(x, dtype=jnp.float32)
                cols["yf"] = jnp.asarray(y, dtype=jnp.float32)
            else:
                bb = garr.bboxes()[perm]
                cols["bxmin"] = jnp.asarray(bb[:, 0], dtype=jnp.float32)
                cols["bymin"] = jnp.asarray(bb[:, 1], dtype=jnp.float32)
                cols["bxmax"] = jnp.asarray(bb[:, 2], dtype=jnp.float32)
                cols["bymax"] = jnp.asarray(bb[:, 3], dtype=jnp.float32)
                # int31-normalized bbox for exact-ish box tests
                cols["bxmin_i"] = jnp.asarray(LON31.normalize(bb[:, 0]), dtype=jnp.int32)
                cols["bymin_i"] = jnp.asarray(LAT31.normalize(bb[:, 1]), dtype=jnp.int32)
                cols["bxmax_i"] = jnp.asarray(LON31.normalize(bb[:, 2]), dtype=jnp.int32)
                cols["bymax_i"] = jnp.asarray(LAT31.normalize(bb[:, 3]), dtype=jnp.int32)

        dtg_attr = table.sft.dtg_attribute
        if dtg_attr is not None and period is not None:
            ms = np.asarray(table.columns[dtg_attr.name], dtype=np.int64)[perm]
            bins, offs = time_to_binned_time(ms, period)
            cols["bin"] = jnp.asarray(bins, dtype=jnp.int32)
            cols["off"] = jnp.asarray(offs, dtype=jnp.int32)

        for attr in table.sft.attributes:
            if attr.is_geometry:
                continue
            raw = table.columns[attr.name]
            if isinstance(raw, StringColumn):
                cols[attr.name] = jnp.asarray(raw.codes[perm], dtype=jnp.int32)
            elif attr.type_name == "Date":
                # seconds resolution on device; exact ms compare via (bin,off)
                # when this is the primary dtg, else host refine
                cols[attr.name] = jnp.asarray(
                    np.asarray(raw, dtype=np.int64)[perm] // 1000, dtype=jnp.int32)
            elif attr.type_name == "Long":
                cols[attr.name] = jnp.asarray(
                    np.asarray(raw)[perm].astype(np.float64), dtype=jnp.float32)
            elif attr.type_name == "Double":
                cols[attr.name] = jnp.asarray(np.asarray(raw)[perm], dtype=jnp.float32)
            else:
                cols[attr.name] = jnp.asarray(np.asarray(raw)[perm])
        return cls(n, cols)
