"""DeviceTable: the HBM-resident columnar projection the scan kernels read.

≙ the data a GeoMesa region/tablet server holds for one index table: rows in
index-key order with the serialized values (SURVEY.md §3.2 step 4). Here the
"rows" are structure-of-arrays jnp buffers in index-sorted order:

  - ``xi``/``yi``  int32 31-bit normalized coords (Z2SFC resolution — exact to
                   ~2 cm; the canonical device coordinates for box tests)
  - ``xf``/``yf``  float32 raw coords (aggregations, joins, density)
  - ``bin``/``off`` int32 exact binned time (period bin + integer offset in
                   period units — ms/s/min, exactly representable)
  - bbox columns (extent geometries): f32 xmin/ymin/xmax/ymax
  - attribute columns: numeric as int32/f32; strings as dictionary codes;
                   dates additionally as (bin, off) when they are the primary
                   temporal axis

Only numeric-representable projections live on device; exact f64 coordinates
and ragged geometry buffers stay host-side for refinement (the reference's
full-filter path).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional

import jax.numpy as jnp
import numpy as np

from geomesa_tpu.curves.binnedtime import TimePeriod, time_to_binned_time
from geomesa_tpu.curves.normalize import NormalizedLat, NormalizedLon
from geomesa_tpu.features.geometry import GeometryArray
from geomesa_tpu.features.table import FeatureTable, StringColumn

LON31 = NormalizedLon(31)
LAT31 = NormalizedLat(31)


def memory_snapshot() -> Dict[str, int]:
    """Live/peak HBM pressure summed over local devices, from each
    backend's ``memory_stats()`` (absent keys are omitted — the CPU
    backend reports nothing, TPU/GPU report live, peak and limit). The
    device-memory gauge feed (metrics.register_device_gauges) and the
    ``debug kernels`` header."""
    import jax
    out: Dict[str, int] = {}
    for d in jax.local_devices():
        stats = getattr(d, "memory_stats", None)
        s = stats() if stats is not None else None
        if not s:
            continue
        for src, dst in (("bytes_in_use", "bytes_in_use"),
                         ("peak_bytes_in_use", "peak_bytes_in_use"),
                         ("bytes_limit", "bytes_limit"),
                         ("num_allocs", "num_allocs")):
            if src in s:
                out[dst] = out.get(dst, 0) + int(s[src])
    return out


def fp62(x, lo: float, hi: float):
    """62-bit fixed-point normalization of a coordinate, split into two int32
    planes (hi = top 31 bits, lo = bottom 31).

    The quantum is (hi-lo)/2^62 ≈ 8e-17 degrees for lon — finer than the f64
    ulp of any real coordinate — so lexicographic (hi, lo) comparison on
    device reproduces the host's f64 predicate exactly up to ties at the f64
    rounding quantum (~4e-14 deg ≈ 4 nm), eliminating the need for any host
    boundary refinement on box predicates. This is the TPU answer to the
    reference's decode-and-compare Z3Filter plus residual exact filter: one
    int compare plane pair instead of two passes.
    """
    x = np.asarray(x, dtype=np.float64)
    if x.ndim == 1 and len(x) >= 65536:
        # bulk encodes take the native one-pass path (bit-identical —
        # tests/test_native.py pins parity); the numpy path below is the
        # canonical semantics and the fallback
        from geomesa_tpu import native
        planes = native.fp62_planes(x, float(lo), float(hi))
        if planes is not None:
            return planes
    frac = np.clip((x - lo) / (hi - lo), 0.0, 1.0)
    # clamp in int64: float(2^62 - 1) rounds UP to 2^62, so a float-side min
    # would let the domain edge overflow the 31-bit hi plane
    v = np.minimum(np.floor(np.ldexp(frac, 62)).astype(np.int64), (1 << 62) - 1)
    return (v >> 31).astype(np.int32), (v & ((1 << 31) - 1)).astype(np.int32)


def fp62_lon(x):
    return fp62(x, -180.0, 180.0)


def fp62_lat(y):
    return fp62(y, -90.0, 90.0)


@dataclass
class DeviceTable:
    """Device-resident columns for one index, in index-sorted row order."""

    n: int
    columns: Dict[str, jnp.ndarray] = field(default_factory=dict)

    def __getitem__(self, name: str) -> jnp.ndarray:
        return self.columns[name]

    def __contains__(self, name: str) -> bool:
        return name in self.columns

    @classmethod
    def build(
        cls,
        table: FeatureTable,
        perm: np.ndarray,
        period: Optional[TimePeriod] = None,
    ) -> "DeviceTable":
        """Project ``table`` rows (reordered by host ``perm``) onto the device.

        period: when set, the default dtg column is decomposed into exact
        (bin, off) int32 pairs for temporal predicates.
        """
        from geomesa_tpu.obs import attrib as _attrib
        planes = host_planes(table, period)
        _attrib.record_transfer(
            "device_table.build", 1,
            sum(int(v.nbytes) for v in planes.values()))
        cols = {k: jnp.asarray(v[perm]) for k, v in planes.items()}
        return cls(len(perm), cols)

    @classmethod
    def build_on_device(
        cls,
        table: FeatureTable,
        dev_perm,
        period: Optional[TimePeriod] = None,
        planes: Optional[Dict[str, np.ndarray]] = None,
    ) -> "DeviceTable":
        """Upload unsorted planes once, then apply the device-resident sort
        permutation with one fused gather — the large-table build path that
        keeps the O(N) reorder on the accelerator instead of the host."""
        import jax

        from geomesa_tpu.obs import attrib as _attrib

        if planes is None:
            planes = host_planes(table, period)
        _attrib.record_transfer(
            "device_table.build_on_device", 1,
            sum(int(v.nbytes) for v in planes.values()))
        unsorted = {k: jnp.asarray(v) for k, v in planes.items()}

        @jax.jit
        def gather(cols, p):
            return {k: v[p] for k, v in cols.items()}

        cols = gather(unsorted, dev_perm)
        return cls(len(table), cols)

    @classmethod
    def merge_scatter(cls, old: "DeviceTable",
                      delta_planes: Dict[str, np.ndarray],
                      r: np.ndarray,
                      stale=(),
                      full_codes: Optional[Dict[str, np.ndarray]] = None,
                      perm_pair=None,
                      host_perm: Optional[np.ndarray] = None):
        """Incremental merge of ``old``'s sorted columns with a sorted delta
        run (the device half of the LSM merge build).

        ``r[j]`` = merged rank of sorted-delta row j among the resident rows
        (count of resident keys ≤ the delta key — residents win ties), host
        int, non-decreasing. The resident shift is derived ON DEVICE from
        ``r`` (searchsorted against iota), so per column only the
        delta-sized values cross the host link — never the resident side.

        ``stale`` columns (dictionary codes whose vocab changed under the
        union-vocab concat) can't reuse the resident device codes; they
        rebuild from ``full_codes`` via one full-length gather through
        ``host_perm`` (host merge) or the merged device perm. ``perm_pair``
        = (old device perm, delta perm values) merges the permutation as
        one more int32 column. Returns (DeviceTable, merged device perm or
        None)."""
        import jax

        from geomesa_tpu.obs import attrib as _attrib

        n_old = old.n
        n_delta = len(r)
        n_new = n_old + n_delta
        full_codes = full_codes or {}

        names = [k for k in old.columns
                 if k in delta_planes and k not in stale]
        old_cols = {k: old.columns[k] for k in names}
        delta_cols = {
            k: jnp.asarray(np.ascontiguousarray(
                np.asarray(delta_planes[k], dtype=old.columns[k].dtype)))
            for k in names}
        if perm_pair is not None:
            old_cols["__perm__"] = perm_pair[0]
            delta_cols["__perm__"] = jnp.asarray(
                np.asarray(perm_pair[1], dtype=np.int32))
        r32 = jnp.asarray(np.asarray(r, dtype=np.int32))
        _attrib.record_transfer(
            "device_table.merge_scatter", 1,
            sum(int(np.asarray(delta_planes[k]).nbytes) for k in names)
            + int(r32.nbytes)
            + sum(int(v.nbytes) for v in full_codes.values()))

        key = (n_old, n_delta,
               tuple(sorted((k, str(v.dtype)) for k, v in old_cols.items())))
        fn = _merge_cache().get(
            key, lambda: _build_merge_scatter(n_old, n_delta))
        out = fn(old_cols, delta_cols, r32)
        new_perm = out.pop("__perm__", None)

        for name in stale:
            codes = full_codes[name]
            if host_perm is not None:
                out[name] = jnp.asarray(codes[host_perm])
            else:
                g = _merge_cache().get(
                    ("stale_gather", n_new, str(codes.dtype)),
                    lambda: jax.jit(lambda c, p: c[p]))
                out[name] = g(jnp.asarray(codes), new_perm)
        return cls(n_new, out), new_perm


_MERGE_CACHE = None


def _merge_cache():
    # lazy: index.scan imports are deferred so device.py stays import-light
    global _MERGE_CACHE
    if _MERGE_CACHE is None:
        from geomesa_tpu.index.scan import ModuleKernelCache
        _MERGE_CACHE = ModuleKernelCache("build.merge_scatter")
    return _MERGE_CACHE


def _build_merge_scatter(n_old: int, n_delta: int):
    import jax

    def fn(old_cols, delta_cols, r):
        shift = jnp.searchsorted(
            r, jnp.arange(n_old, dtype=jnp.int32),
            side="right").astype(jnp.int32)
        pos_res = jnp.arange(n_old, dtype=jnp.int32) + shift
        pos_del = r + jnp.arange(n_delta, dtype=jnp.int32)
        out = {}
        for k, o in old_cols.items():
            d = delta_cols[k]
            buf = jnp.zeros((n_old + n_delta,) + tuple(o.shape[1:]), o.dtype)
            out[k] = buf.at[pos_res].set(o).at[pos_del].set(d)
        return out

    return jax.jit(fn)


def host_planes(table: FeatureTable,
                period: Optional[TimePeriod] = None,
                skip_geom: bool = False,
                skip_dtg: bool = False) -> Dict[str, np.ndarray]:
    """Unsorted numpy projection of ``table`` onto the device column layout
    (row order = table order; the caller applies the index sort).

    ``skip_geom``/``skip_dtg`` omit the geometry / binned-time planes when the
    caller already produced them (the native fused-encode build path)."""
    cols: Dict[str, np.ndarray] = {}

    geom_attr = table.sft.geometry_attribute
    if skip_geom:
        geom_attr = None
    if geom_attr is not None:
        garr: GeometryArray = table.columns[geom_attr.name]
        if garr.is_points:
            x, y = garr.point_xy()
            xi, xl = fp62_lon(x)
            yi, yl = fp62_lat(y)
            cols["xi"], cols["xl"] = xi, xl
            cols["yi"], cols["yl"] = yi, yl
            cols["xf"] = np.asarray(x, dtype=np.float32)
            cols["yf"] = np.asarray(y, dtype=np.float32)
        else:
            bb = garr.bboxes()
            cols["bxmin"] = np.asarray(bb[:, 0], dtype=np.float32)
            cols["bymin"] = np.asarray(bb[:, 1], dtype=np.float32)
            cols["bxmax"] = np.asarray(bb[:, 2], dtype=np.float32)
            cols["bymax"] = np.asarray(bb[:, 3], dtype=np.float32)
            # fp62 envelope planes: exact envelope-overlap tests on device
            for name, vals, f in (("bxmin", bb[:, 0], fp62_lon),
                                  ("bymin", bb[:, 1], fp62_lat),
                                  ("bxmax", bb[:, 2], fp62_lon),
                                  ("bymax", bb[:, 3], fp62_lat)):
                hi, lo = f(vals)
                cols[name + "_i"] = hi
                cols[name + "_l"] = lo

    dtg_attr = table.sft.dtg_attribute
    if dtg_attr is not None and period is not None and not skip_dtg:
        ms = np.asarray(table.columns[dtg_attr.name], dtype=np.int64)
        bins, offs = time_to_binned_time(ms, period)
        cols["bin"] = np.asarray(bins, dtype=np.int32)
        cols["off"] = np.asarray(offs, dtype=np.int32)

    if table.visibility is not None:
        # dictionary codes; query-time auths shrink to an allowed-code set
        cols["__vis__"] = np.asarray(table.visibility.codes, dtype=np.int32)

    group = table.sft.device_column_group
    for attr in table.sft.attributes:
        if attr.is_geometry:
            continue
        if group is not None and attr.name not in group \
                and not (dtg_attr is not None and attr.name == dtg_attr.name):
            continue  # outside the device column group: host-only attribute
        raw = table.columns[attr.name]
        if isinstance(raw, StringColumn):
            cols[attr.name] = np.asarray(raw.codes, dtype=np.int32)
        elif attr.type_name == "Date":
            if dtg_attr is not None and attr.name == dtg_attr.name \
                    and period is not None:
                continue  # (bin, off) planes carry the primary dtg exactly
            # secondary date attrs: seconds resolution on device (residual
            # date predicates are host-refined; this column is advisory)
            cols[attr.name] = (np.asarray(raw, dtype=np.int64) // 1000).astype(np.int32)
        elif attr.type_name == "Long":
            cols[attr.name] = np.asarray(raw).astype(np.float64).astype(np.float32)
        elif attr.type_name == "Double":
            cols[attr.name] = np.asarray(raw, dtype=np.float32)
        else:
            cols[attr.name] = np.asarray(raw)
    return cols
