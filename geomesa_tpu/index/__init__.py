"""Index core (≙ reference geomesa-index-api, SURVEY.md §2.4).

GeoMesa's architecture separates pure key math, host planning, and
data-parallel scan+filter; this package keeps that split TPU-natively:

  - ``device``    — DeviceTable: the HBM-resident columnar projection of a
                    FeatureTable in index-sorted order (the "server-side data")
  - ``scan``      — jitted mask kernels (≙ Z3Filter/Z2Filter push-down filters
                    + CqlTransformFilter residual evaluation)
  - ``z2/z3/xz2/xz3/attribute/ids`` — index implementations (key encode, sort,
                    range planning) (≙ index.index.* key spaces)
  - ``planner``   — FilterSplitter / StrategyDecider / QueryPlanner
  - ``api``       — shared plan/result datatypes
"""

from geomesa_tpu.index.api import IndexScanPlan, QueryResult
from geomesa_tpu.index.planner import QueryPlanner

__all__ = ["IndexScanPlan", "QueryResult", "QueryPlanner"]
