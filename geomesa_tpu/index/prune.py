"""Range-pruned scan execution (host planning side).

≙ the reference's core query model: decompose the query region into at most
``geomesa.scan.ranges.target`` (2000) key ranges and scan only those
(Z3IndexKeySpace.getRanges, /root/reference/geomesa-index-api/src/main/scala/
org/locationtech/geomesa/index/index/z3/Z3IndexKeySpace.scala:162-189;
QueryProperties.scala:22). Here the "tablet ranges" become row intervals of
the index's sorted order, found by binary search over the host-resident
sorted key arrays, then converted to fixed-size *blocks* — small int32 ids
the device turns back into row indices with an iota, so a pruned scan ships
a few hundred ints instead of millions of row positions. The device kernel
gathers candidate blocks and re-applies the full exact mask, so the cover
only ever needs to be a superset (block granularity and cover slop are
harmless).

The planner prefers the pruned path when the candidate fraction is small
(``PRUNE_MAX_FRACTION``); above that a full-table fused mask scan is faster
than gathering (sequential HBM beats scattered gathers once most blocks are
touched anyway).
"""

from __future__ import annotations

import sys
from typing import List, Optional, Sequence, Tuple

import numpy as np

from geomesa_tpu import config
from geomesa_tpu.curves.ranges import IndexRange

# MAX_RANGES / BLOCK_SIZE / PRUNE_MAX_FRACTION resolve through the config
# registry on EVERY access (PEP 562 module __getattr__ below), so env/set()
# overrides take effect at runtime; tests may still monkeypatch the module
# attribute directly (a real attribute shadows __getattr__).
#   MAX_RANGES         ≙ geomesa.scan.ranges.target (QueryProperties.scala:22)
#   BLOCK_SIZE         rows per gather block (coalesced HBM reads vs slop)
#   PRUNE_MAX_FRACTION above this candidate fraction a full scan wins
_CONFIG_ATTRS = {
    "MAX_RANGES": "SCAN_RANGES_TARGET",
    "BLOCK_SIZE": "PRUNE_BLOCK",
    "PRUNE_MAX_FRACTION": "PRUNE_MAX_FRACTION",
}


def __getattr__(name: str):
    prop = _CONFIG_ATTRS.get(name)
    if prop is None:
        raise AttributeError(name)
    return getattr(config, prop).get()
# cap on per-query interval decomposition (bins), mirroring the reference's
# per-epoch range decomposition limits
MAX_BINS = 512


def ranges_to_slices(sorted_keys: np.ndarray,
                     ranges,
                     base: int = 0,
                     lo: int = 0,
                     hi: Optional[int] = None) -> np.ndarray:
    """Inclusive key ranges → [lo, hi) row slices via binary search over one
    contiguous segment of a sorted key array. Returns (S, 2) int64.

    ``ranges``: a Sequence[IndexRange], or the array form — a (lo, hi, ...)
    tuple of int64 arrays (the hot path: sfc.ranges_arrays feeds this with
    no per-range Python objects)."""
    if hi is None:
        hi = len(sorted_keys)
    if (isinstance(ranges, tuple) and len(ranges) >= 2
            and isinstance(ranges[0], np.ndarray)):
        # the array form; a tuple OF IndexRange objects (legal under the
        # Sequence contract) falls through to the object branch below
        lowers, uppers = ranges[0], ranges[1]
    elif ranges:
        lowers = np.fromiter((r.lower for r in ranges), np.int64, len(ranges))
        uppers = np.fromiter((r.upper for r in ranges), np.int64, len(ranges))
    else:
        lowers = uppers = np.empty(0, np.int64)
    if len(lowers) == 0 or lo >= hi:
        return np.empty((0, 2), dtype=np.int64)
    seg = sorted_keys[lo:hi]
    starts = np.searchsorted(seg, lowers, side="left") + lo + base
    stops = np.searchsorted(seg, uppers, side="right") + lo + base
    keep = stops > starts
    return np.stack([starts[keep], stops[keep]], axis=1)


def slices_to_blocks(slices: np.ndarray, n_rows: int,
                     block_size: Optional[int] = None) -> Optional[np.ndarray]:
    """Row slices → sorted unique block ids (int32). None when the expansion
    would be degenerate (no slices). ``block_size`` defaults to the *current*
    module BLOCK_SIZE (late-bound so runtime/test overrides take effect)."""
    if block_size is None:
        block_size = sys.modules[__name__].BLOCK_SIZE
    if len(slices) == 0:
        return None
    last = max(0, (n_rows - 1) // block_size)
    lo_b = np.minimum(slices[:, 0] // block_size, last)
    hi_b = np.minimum((slices[:, 1] - 1) // block_size, last)
    counts = (hi_b - lo_b + 1)
    total = int(counts.sum())
    # expand each [lo_b, hi_b] run with a ragged iota
    offsets = np.repeat(np.cumsum(counts) - counts, counts)
    ids = np.repeat(lo_b, counts) + (np.arange(total) - offsets)
    return np.unique(ids).astype(np.int32)


def candidate_stats(slices: np.ndarray, blocks: Optional[np.ndarray],
                    n_rows: int, block_size: Optional[int] = None) -> dict:
    """Explain payload for a pruned plan."""
    if block_size is None:
        block_size = sys.modules[__name__].BLOCK_SIZE
    rows = int((slices[:, 1] - slices[:, 0]).sum()) if len(slices) else 0
    nb = 0 if blocks is None else len(blocks)
    return {
        "candidate_rows": rows,
        "candidate_blocks": nb,
        "scanned_rows": nb * block_size,
        "scanned_fraction": round(nb * block_size / max(1, n_rows), 5),
    }


def bin_windows(intervals, period) -> Optional[List[Tuple[int, Tuple[int, int]]]]:
    """Decompose time intervals into per-bin in-bin offset windows:
    [(bin, (t_lo, t_hi))...], t in period offset units, inclusive.

    ≙ Z3IndexKeySpace.getIndexValues' per-epoch time decomposition
    (Z3IndexKeySpace.scala:98-160). None when the decomposition explodes
    (> MAX_BINS bins) — callers fall back to the unpruned scan.
    """
    from geomesa_tpu.curves.binnedtime import max_offset, time_to_binned_time

    out: List[Tuple[int, Tuple[int, int]]] = []
    mo = max_offset(period) - 1
    for lo, hi in intervals:
        blo, olo = time_to_binned_time(int(lo), period)
        bhi, ohi = time_to_binned_time(int(hi), period)
        blo, olo, bhi, ohi = int(blo), int(olo), int(bhi), int(ohi)
        if bhi - blo + 1 > MAX_BINS or len(out) + (bhi - blo + 1) > MAX_BINS:
            return None
        for b in range(blo, bhi + 1):
            t0 = olo if b == blo else 0
            t1 = ohi if b == bhi else mo
            out.append((b, (t0, min(t1, mo))))
    return out


class BinSegments:
    """Per-bin contiguous row segments of an epoch-major sorted index
    (lazy; one linear pass over the sorted bins array, cached)."""

    def __init__(self, sorted_bins: np.ndarray):
        bins = np.asarray(sorted_bins)
        if len(bins) == 0:
            self.bins = np.empty(0, np.int64)
            self.starts = np.zeros(1, np.int64)
            return
        change = np.flatnonzero(np.diff(bins)) + 1
        self.bins = np.concatenate([[bins[0]], bins[change]]).astype(np.int64)
        self.starts = np.concatenate(
            [[0], change, [len(bins)]]).astype(np.int64)

    def segment(self, b: int) -> Tuple[int, int]:
        """[lo, hi) rows of bin ``b`` (empty slice when absent)."""
        i = int(np.searchsorted(self.bins, b))
        if i == len(self.bins) or self.bins[i] != b:
            return 0, 0
        return int(self.starts[i]), int(self.starts[i + 1])

    def all_bins(self) -> np.ndarray:
        return self.bins
