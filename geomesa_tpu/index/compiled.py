"""Single-dispatch query compilation: one plan *shape* → ONE device program.

≙ the reference's server-side push-down taken to its limit: instead of the
host orchestrating plan → range-decompose → scan → refine as separate device
rounds (each paying the dispatch floor ``bench.py`` tracks as
``dispatch_floor_ms_per_query``), a qualifying plan shape compiles into a
single jitted program that does cover/block selection, the primary scan, the
lowered residual predicate, and the aggregate in ONE dispatch with ONE
host→device round trip.

Three layers:

1. **IR lowering** (``_lower_residual``): walks the filter-IR tree and emits
   the residual mask directly into the program, mirroring
   ``scan.compile_residual``'s structure-key grammar EXACTLY (the lowered key
   must reproduce the interpreted key, or we fall back) — but constants land
   in ONE packed int32 vector instead of a params list, so a whole query
   ships as a single warm-shaped transfer inside the dispatch.

2. **In-kernel cover selection**: per-block f32 coordinate (and time-bin)
   summaries live on device; the program gates blocks against the query's
   f32 envelope (slack-expanded superset — the exact fp62 mask re-applies to
   every gathered row), gathers up to CAP candidate blocks, and falls back to
   the full-table mask *inside the same program* (``lax.cond``) when the
   candidate set overflows. The program is total: no host-visible overflow
   round trip for counts.

3. **Shape-keyed caching + recipe fast path**: programs key by the same
   normalized structure signature discipline as the plan cache (geometry is
   data, shape is structure — N distinct bboxes of one shape compile ONCE),
   bounded in a ``ModuleKernelCache`` LRU and counted in ``kernels.compiled``.
   A per-planner recipe cache additionally maps (filter shape, auths) →
   bind instructions, so a repeat *shape* skips ``planner.plan()`` and range
   decomposition entirely: extract boxes/windows, pack, dispatch.

Union (OR-of-covers) plans lower too when every branch is a device-exact
point_boxes scan on one index: the per-branch masks OR inside a single
program (``_build_union``), so union selects and density grids are one
dispatch with inherent dedup instead of per-branch scans + host unions.

Geometry-catalog residuals (geom/catalog.py st_* calls) ride the refine
modes: ``st_contains(POLYGON, geom)`` / ``st_intersects(geom, POLYGON)``
lower to the certainty-band point-in-polygon classifier and
``st_distance(geom, POINT) < r`` to a banded radial test (``_refine_spec``);
the uncertain sliver re-evaluates on host in exact f64 either way.

Fallback rules (always exact — the staged path is the oracle): attribute
-index plans, FID filters, union plans with host residuals or mixed
indexes, vocab-less string predicates, host residuals other than the
single-predicate refine shapes above over point layers, tables under 4
gather blocks, and any structure-key drift between the lowered and
interpreted residuals.

Knobs: ``GEOMESA_TPU_FUSED_QUERY`` (master switch),
``GEOMESA_TPU_PALLAS_REFINE`` (Pallas point-in-polygon inner loop),
``GEOMESA_TPU_FUSED_SHAPE_CACHE`` (recipe LRU bound),
``GEOMESA_TPU_KERNEL_CACHE`` (compiled program LRU bound).
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from geomesa_tpu import config
from geomesa_tpu import trace as _trace
from geomesa_tpu.filter import ir
from geomesa_tpu.filter.extract import extract_bboxes, extract_intervals
from geomesa_tpu.index import prune as _prune
from geomesa_tpu.index.scan import (EMPTY_BOX, EMPTY_WINDOW, PRIMARY_FNS,
                                    ModuleKernelCache, ScanKernels,
                                    Unsupported, _LazyBlockGather, _fetch,
                                    _grid_scatter, _pip_band, _time_mask,
                                    pad_boxes, pad_windows, split_residual)
from geomesa_tpu.index.spatial import _boxes_fp62, _strip_handled
from geomesa_tpu.curves.binnedtime import time_to_binned_time
from geomesa_tpu.metrics import REGISTRY
from geomesa_tpu.obs import attrib as _attrib
from geomesa_tpu.serve.resilience import deadline as _rdl

# module-level program cache: LRU-bounded by GEOMESA_TPU_KERNEL_CACHE,
# registered in _KERNEL_INSTANCES so fused programs count in the
# kernels.compiled gauge and the PR-6 recompile detector exactly like the
# staged scan kernels they replace
_PROGRAMS = ModuleKernelCache("fused_query")

# observable ledger for tests and the debug/healthz surfaces
STATS: Dict[str, int] = {
    "queries": 0,          # dispatches served by a fused program
    "fallbacks": 0,        # qualification declines (staged path served)
    "programs_built": 0,   # distinct program compiles
    "shape_hits": 0,       # recipe fast-path binds (no planner.plan at all)
    "shape_misses": 0,     # shapes seen before a recipe existed
    "bind_failures": 0,    # recipe present but the new values didn't bind
    "overflow_retries": 0, # select capacity regrows
}

REGISTRY.set_gauge("fused.programs", lambda: len(_PROGRAMS._jitted))

# block-gate slack in degrees: the per-block summaries are f32 reductions of
# the f32 coordinate planes and the gate envelopes are f32 roundings of f64
# query bounds — both within _IN_DELTA (2.5e-5) of exact. 1e-3 deg is >>
# both, so a gated-out block provably contains no match (the exact fp62 mask
# re-applies inside the gathered blocks either way).
_GATE_SLACK = np.float32(1e-3)

# select-capacity tiers shared with planner._SELECT_TIERS (each distinct
# capacity is its own compile; hints quantize UP)
_SELECT_TIERS = (1 << 10, 1 << 13, 1 << 16, 1 << 19, 1 << 22)

_UNC_CAP = 4096  # refine-mode uncertain-row capacity (host fallback past it)

# radial-distance certainty band (degrees) for the "dist" refine kind: must
# exceed the f32 error of hypot over the f32 coordinate planes — coordinate
# rounding ≤ 2.5e-5 per axis plus a few ulp of arithmetic at |coord| ≤ 360
# (< 5e-4 total) plus the radius literal's own f32 cast (≤ 2.2e-5). Rows
# inside the band re-evaluate on host in exact f64.
_DIST_BAND = np.float32(1e-3)


def _pow2(x: int) -> int:
    return max(1, 1 << max(0, int(x) - 1).bit_length())


def _tier(capacity: Optional[int]) -> int:
    if capacity is None:
        return 1 << 16
    for t in _SELECT_TIERS:
        if capacity <= t:
            return t
    return _pow2(capacity)


# -- packed constant layout ---------------------------------------------------


class _Layout:
    """Every per-query constant (boxes, gate, windows, residual values, vis
    codes, edges, grid) packs into ONE pow2-padded int32 vector — one warm
    transfer shape per program, shipped with the dispatch. f32 slots ride as
    bit patterns (``view``/``bitcast_convert_type``)."""

    def __init__(self):
        self.slots: List[tuple] = []   # (offset, size, shape, is_f32)
        self._n = 0

    def add(self, shape: tuple, f32: bool = False) -> int:
        size = 1
        for d in shape:
            size *= int(d)
        self.slots.append((self._n, size, tuple(shape), bool(f32)))
        self._n += size
        return len(self.slots) - 1

    @property
    def padded(self) -> int:
        return _pow2(max(8, self._n))

    def signature(self) -> tuple:
        """Value-free structural signature (part of the program key)."""
        return tuple((size, shape, f32) for _, size, shape, f32 in self.slots)

    def pack(self, values: list) -> np.ndarray:
        out = np.zeros(self.padded, dtype=np.int32)
        for (off, size, shape, f32), v in zip(self.slots, values):
            if f32:
                a = np.ascontiguousarray(v, dtype=np.float32)
                out[off:off + size] = a.reshape(-1).view(np.int32)
            else:
                out[off:off + size] = np.asarray(
                    v, dtype=np.int32).reshape(-1)
        return out


def _make_get(slots: tuple) -> Callable:
    """In-kernel unpack: static slices + bitcast, so unpacking fuses away."""
    import jax
    import jax.numpy as jnp

    def get(packed, i: int):
        off, size, shape, f32 = slots[i]
        v = packed[off:off + size]
        if f32:
            v = jax.lax.bitcast_convert_type(v, jnp.float32)
        return v.reshape(shape) if shape else v[0]

    return get


# -- residual IR lowering -----------------------------------------------------

# attr type names whose device columns are exact (mirrors scan.py)
_EXACT_DEVICE_TYPES = {"Int", "Integer", "Boolean", "String", "Float"}


def _lower_residual(f: Optional[ir.Filter], sft, string_vocabs,
                    available: Optional[set], layout: _Layout, values: list):
    """``compile_residual``'s twin: same structure-key grammar and the same
    ``Unsupported`` conditions, but constants allocate packed layout slots
    and the emitted fn reads them back through ``get``. Returns
    (structure_key, emit | None) where emit(cols, packed, get) → bool mask.
    """
    import functools

    import jax.numpy as jnp

    if f is None:
        return "none", None

    def check_available(attr: str) -> None:
        if available is not None and attr not in available:
            raise Unsupported(f"{attr} not in the device column group")

    def const(v, f32: bool = False, shape: tuple = ()) -> int:
        values.append(v)
        return layout.add(shape, f32)

    def walk(node: ir.Filter):
        if isinstance(node, ir.Include):
            return "inc", lambda cols, p, get: jnp.ones(
                next(iter(cols.values())).shape[0], dtype=bool)
        if isinstance(node, ir.Exclude):
            return "exc", lambda cols, p, get: jnp.zeros(
                next(iter(cols.values())).shape[0], dtype=bool)
        if isinstance(node, ir.And):
            keys, fns = zip(*(walk(c) for c in node.children))
            return "and(" + ",".join(keys) + ")", \
                lambda cols, p, get, fns=fns: functools.reduce(
                    jnp.logical_and, [g(cols, p, get) for g in fns])
        if isinstance(node, ir.Or):
            keys, fns = zip(*(walk(c) for c in node.children))
            return "or(" + ",".join(keys) + ")", \
                lambda cols, p, get, fns=fns: functools.reduce(
                    jnp.logical_or, [g(cols, p, get) for g in fns])
        if isinstance(node, ir.Not):
            k, g = walk(node.child)
            return f"not({k})", lambda cols, p, get, g=g: ~g(cols, p, get)
        if isinstance(node, ir.Cmp):
            check_available(node.attr)
            attr = sft.attribute(node.attr)
            if attr.type_name == "String":
                if node.op not in ("=", "<>"):
                    raise Unsupported("ordered string cmp on device")
                vocab = string_vocabs.get(node.attr)
                if vocab is None:
                    raise Unsupported("no vocab")
                try:
                    code = vocab.index(node.value)
                except ValueError:
                    code = -1  # matches nothing
                i = const(code)
                if node.op == "=":
                    return f"seq:{node.attr}", \
                        lambda cols, p, get, i=i, a=node.attr: \
                        cols[a] == get(p, i)
                return f"sne:{node.attr}", \
                    lambda cols, p, get, i=i, a=node.attr: \
                    cols[a] != get(p, i)
            if attr.type_name not in _EXACT_DEVICE_TYPES:
                raise Unsupported(f"{attr.type_name} cmp is inexact on device")
            i = const(node.value, f32=(attr.type_name == "Float"))
            op = node.op
            key = f"cmp{op}:{node.attr}"

            def g(cols, p, get, i=i, a=node.attr, op=op):
                c = cols[a]
                v = get(p, i)
                return {"=": c == v, "<>": c != v, "<": c < v,
                        "<=": c <= v, ">": c > v, ">=": c >= v}[op]
            return key, g
        if isinstance(node, ir.In):
            check_available(node.attr)
            attr = sft.attribute(node.attr)
            if attr.type_name == "String":
                vocab = string_vocabs.get(node.attr)
                if vocab is None:
                    raise Unsupported("no vocab")
                codes = [vocab.index(v) for v in node.values if v in vocab] \
                    or [-1]
            elif attr.type_name in ("Int", "Integer"):
                codes = [int(v) for v in node.values]
            else:
                raise Unsupported("IN on non-int/string")
            size = max(1, 1 << (len(codes) - 1).bit_length())
            padded = codes + [codes[-1]] * (size - len(codes))
            i = const(padded, shape=(size,))
            return f"in{size}:{node.attr}", \
                lambda cols, p, get, i=i, a=node.attr: jnp.any(
                    cols[a][:, None] == get(p, i)[None, :], axis=1)
        if isinstance(node, ir.During):
            raise Unsupported("During handled by primary time windows")
        raise Unsupported(type(node).__name__)

    return walk(f)


# -- per-block device summaries (the in-kernel cover) -------------------------


def _block_summaries(index, bsz: int):
    """Per-gather-block coordinate (and time-bin) envelopes, resident on
    device and cached on the index. The program's block gate tests query
    envelopes against these — a slack-expanded superset of the block's rows
    (invalid/padded rows fold to ∓inf so they never keep a block alive)."""
    cached = getattr(index, "_fused_summ", None)
    if cached is not None and cached[0] == bsz:
        return cached[1]
    import jax
    import jax.numpy as jnp

    cols = index.device.columns
    n = int(cols["xf"].shape[0])
    nb = -(-n // bsz)
    pad = nb * bsz - n
    valid = cols.get("__valid__")

    def blocked(c, fill):
        if valid is not None:
            c = jnp.where(valid, c, fill)
        if pad:
            c = jnp.concatenate([c, jnp.full((pad,), fill, c.dtype)])
        return c.reshape(nb, bsz)

    inf = jnp.float32(np.inf)
    summ = {
        "bxmin": jnp.min(blocked(cols["xf"], inf), axis=1) - _GATE_SLACK,
        "bxmax": jnp.max(blocked(cols["xf"], -inf), axis=1) + _GATE_SLACK,
        "bymin": jnp.min(blocked(cols["yf"], inf), axis=1) - _GATE_SLACK,
        "bymax": jnp.max(blocked(cols["yf"], -inf), axis=1) + _GATE_SLACK,
    }
    if "bin" in cols:
        lo = jnp.int32(-(1 << 31) + 1)
        hi = jnp.int32((1 << 31) - 1)
        summ["binmin"] = jnp.min(blocked(cols["bin"], hi), axis=1)
        summ["binmax"] = jnp.max(blocked(cols["bin"], lo), axis=1)
    jax.block_until_ready(summ)
    index._fused_summ = (bsz, summ)
    return summ


# -- Pallas point-in-polygon refine prototype --------------------------------


_PALLAS_OK: Optional[bool] = None


def _pallas_pip(px, py, edges):
    """Pallas tiling of the certainty-band point-in-polygon classifier:
    point tiles stream through VMEM against the full resident edge table.
    CPU-safe via interpret mode (non-TPU backends)."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    n = int(px.shape[0])
    ne = int(edges.shape[0])
    tile = 512 if n >= 512 else _pow2(n)
    npad = -(-n // tile) * tile
    if npad != n:
        far = jnp.full((npad - n,), 1e9, jnp.float32)
        px = jnp.concatenate([px, far])   # pad rows classify certain-out
        py = jnp.concatenate([py, far])

    def kernel(px_ref, py_ref, e_ref, cin_ref, cout_ref):
        e = e_ref[...]
        cin, cout = _pip_band(
            px_ref[...][:, None], py_ref[...][:, None],
            e[None, :, 0], e[None, :, 1], e[None, :, 2], e[None, :, 3])
        cin_ref[...] = cin
        cout_ref[...] = cout

    cin, cout = pl.pallas_call(
        kernel,
        out_shape=[jax.ShapeDtypeStruct((npad,), jnp.bool_)] * 2,
        grid=(npad // tile,),
        in_specs=[pl.BlockSpec((tile,), lambda i: (i,)),
                  pl.BlockSpec((tile,), lambda i: (i,)),
                  pl.BlockSpec((ne, 4), lambda i: (0, 0))],
        out_specs=[pl.BlockSpec((tile,), lambda i: (i,))] * 2,
        interpret=jax.default_backend() != "tpu",
    )(px, py, edges)
    return cin[:n], cout[:n]


def _pallas_available() -> bool:
    """GEOMESA_TPU_PALLAS_REFINE gate + a one-time eager probe: any failure
    (backend without pallas lowering) permanently falls back to the jnp
    band kernel, so the knob can never break correctness."""
    if not config.PALLAS_REFINE.get():
        return False
    global _PALLAS_OK
    if _PALLAS_OK is None:
        try:
            import jax.numpy as jnp
            ep = jnp.asarray(np.tile(ScanKernels._EDGE_PAD, (4, 1)))
            z = jnp.zeros(4, jnp.float32)
            _PALLAS_OK = bool(np.asarray(_pallas_pip(z, z, ep)[1]).all())
        except Exception:
            _PALLAS_OK = False
    return _PALLAS_OK


def _pip_flags(px, py, edges, use_pallas: bool):
    if use_pallas:
        return _pallas_pip(px, py, edges)
    return _pip_band(px[:, None], py[:, None],
                     edges[None, :, 0], edges[None, :, 1],
                     edges[None, :, 2], edges[None, :, 3])


# -- the fused program --------------------------------------------------------


class _Program:
    """A compiled fused program bound to one query's packed constants."""

    __slots__ = ("fn", "cols", "summ", "packed", "mode", "sel_cap",
                 "unc_cap", "n", "res_key", "key", "layout")

    def __init__(self, fn, cols, summ, packed, mode, sel_cap, unc_cap, n,
                 res_key, key, layout=None):
        self.fn = fn
        self.cols = cols
        self.summ = summ
        self.packed = packed   # host np; ships WITH the dispatch (one round)
        self.mode = mode
        self.sel_cap = sel_cap
        self.unc_cap = unc_cap
        self.n = n
        self.res_key = res_key
        self.key = key
        self.layout = layout   # set by _build; the template-rebind fast path

    def dispatch(self):
        """The single dispatch: packed constants ride into the jit call, the
        returned device value syncs only when the caller reads it."""
        return self.fn(self.cols, self.summ, self.packed)


def _jit_program(mode: str, slots: tuple, six: Dict[str, int], emit,
                 T: int, n: int, bsz: int, cap: int, sel_cap: int,
                 unc_cap: int, use_pallas: bool, has_bin: bool,
                 width: int, height: int, refine: str = "pip"):
    """Build + jit one fused program. Everything here is structure; values
    arrive through the packed vector at dispatch time."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    get = _make_get(slots)
    total = cap * bsz

    def run(cols, summ, packed):
        boxes = get(packed, six["boxes"])
        gate = get(packed, six["gate"])
        windows = get(packed, six["windows"]) if T else None

        # -- in-kernel cover: which blocks can possibly match -------------
        alive = jnp.any(
            (summ["bxmax"][:, None] >= gate[None, :, 0])
            & (summ["bxmin"][:, None] <= gate[None, :, 2])
            & (summ["bymax"][:, None] >= gate[None, :, 1])
            & (summ["bymin"][:, None] <= gate[None, :, 3]), axis=1)
        if T and has_bin:
            blo, bhi = windows[:, 0], windows[:, 2]
            alive = alive & jnp.any(
                (blo <= bhi)[None, :]
                & (summ["binmin"][:, None] <= bhi[None, :])
                & (summ["binmax"][:, None] >= blo[None, :]), axis=1)
        n_alive = jnp.sum(alive)

        def mask_of(c, membership=None):
            m = PRIMARY_FNS["point_boxes"](c, boxes)
            if T:
                m = m & _time_mask(c, windows)
            if emit is not None:
                m = m & emit(c, packed, get)
            if "vis" in six:
                codes = get(packed, six["vis"])
                m = m & jnp.any(
                    c["__vis__"][:, None] == codes[None, :], axis=1)
            if "__valid__" in c:
                m = m & c["__valid__"]
            if membership is not None:
                m = m & membership
            return m

        def gathered():
            # scan.py expand_blocks discipline: clamped starts re-read a
            # suffix of the previous block; the membership test masks the
            # re-reads and -1 pads without double counts
            bids = jnp.nonzero(
                alive, size=cap, fill_value=-1)[0].astype(jnp.int32)
            bids = jnp.where(bids < nb_blocks, bids, -1)
            starts = bids * bsz
            astart = jnp.clip(starts, 0, max(0, n - bsz))
            rows = astart[:, None] + jnp.arange(bsz, dtype=jnp.int32)[None, :]
            membership = ((bids >= 0)[:, None]
                          & (rows >= starts[:, None])
                          & (rows < starts[:, None] + bsz)).reshape(-1)
            g = _LazyBlockGather(cols, astart, bsz, total)
            return mask_of(g, membership), rows.reshape(-1), g

        def refine_of(c, m):
            if refine == "dist":
                dz = get(packed, six["dist"])
                d = jnp.sqrt((c["xf"] - dz[0]) ** 2 + (c["yf"] - dz[1]) ** 2)
                cin = d <= dz[2] - _DIST_BAND
                cout = d >= dz[2] + _DIST_BAND
            else:
                edges = get(packed, six["edges"])
                cin, cout = _pip_flags(c["xf"], c["yf"], edges, use_pallas)
            return m & cin, m & ~cin & ~cout

        if mode == "count":
            def pruned(_):
                m, _, _ = gathered()
                return jnp.sum(m).astype(jnp.int32)

            def full(_):
                return jnp.sum(mask_of(cols)).astype(jnp.int32)

            return lax.cond(n_alive <= cap, pruned, full, 0)

        if mode == "select":
            def pruned(_):
                m, rowids, _ = gathered()
                sel = jnp.nonzero(m, size=sel_cap, fill_value=total)[0]
                rows = jnp.where(
                    sel < total, rowids[jnp.clip(sel, 0, total - 1)], n)
                return jnp.concatenate([
                    jnp.sum(m)[None].astype(jnp.int32),
                    rows.astype(jnp.int32)])

            def full(_):
                m = mask_of(cols)
                sel = jnp.nonzero(m, size=sel_cap, fill_value=n)[0]
                return jnp.concatenate([
                    jnp.sum(m)[None].astype(jnp.int32),
                    sel.astype(jnp.int32)])

            return lax.cond(n_alive <= cap, pruned, full, 0)

        if mode in ("count_refine", "select_refine"):
            def pruned(_):
                m, rowids, g = gathered()
                hit, unc = refine_of(g, m)
                parts = [jnp.sum(hit)[None].astype(jnp.int32),
                         jnp.sum(unc)[None].astype(jnp.int32)]
                if mode == "select_refine":
                    s = jnp.nonzero(hit, size=sel_cap, fill_value=total)[0]
                    parts.append(jnp.where(
                        s < total, rowids[jnp.clip(s, 0, total - 1)],
                        n).astype(jnp.int32))
                u = jnp.nonzero(unc, size=unc_cap, fill_value=total)[0]
                parts.append(jnp.where(
                    u < total, rowids[jnp.clip(u, 0, total - 1)],
                    n).astype(jnp.int32))
                return jnp.concatenate(parts)

            def full(_):
                m = mask_of(cols)
                hit, unc = refine_of(cols, m)
                parts = [jnp.sum(hit)[None].astype(jnp.int32),
                         jnp.sum(unc)[None].astype(jnp.int32)]
                if mode == "select_refine":
                    parts.append(jnp.nonzero(
                        hit, size=sel_cap,
                        fill_value=n)[0].astype(jnp.int32))
                parts.append(jnp.nonzero(
                    unc, size=unc_cap, fill_value=n)[0].astype(jnp.int32))
                return jnp.concatenate(parts)

            return lax.cond(n_alive <= cap, pruned, full, 0)

        if mode == "density":
            grid = get(packed, six["grid"])

            def pruned(_):
                m, _, g = gathered()
                return (_grid_scatter(g["xf"], g["yf"], m, None, grid,
                                      width, height),
                        jnp.sum(m).astype(jnp.int32))

            def full(_):
                m = mask_of(cols)
                return (_grid_scatter(cols["xf"], cols["yf"], m, None, grid,
                                      width, height),
                        jnp.sum(m).astype(jnp.int32))

            return lax.cond(n_alive <= cap, pruned, full, 0)

        raise ValueError(mode)

    nb_blocks = -(-n // bsz)
    STATS["programs_built"] += 1
    jitted = jax.jit(run)
    if _attrib.enabled():
        jitted = _attrib.compile_probe(jitted, f"fused_{mode}.point_boxes",
                                       cap)
    return jitted


def _gate_of(boxes_geo, B: int) -> np.ndarray:
    """(B, 4) f32 [xmin, ymin, xmax, ymax] block-gate envelopes; padded rows
    are inverted (nothing alive)."""
    gate = np.empty((B, 4), dtype=np.float32)
    gate[:, 0] = 3e38
    gate[:, 1] = 3e38
    gate[:, 2] = -3e38
    gate[:, 3] = -3e38
    for i, (xmin, ymin, xmax, ymax) in enumerate(boxes_geo):
        gate[i] = (xmin, ymin, xmax, ymax)
    return gate


def _build(index, sft, vocabs, mode: str, boxes: np.ndarray,
           gate: np.ndarray, windows: Optional[np.ndarray], dev_ir,
           vis: Optional[np.ndarray],
           refine_spec: Optional[Tuple[str, np.ndarray]],
           grid, width: int, height: int, capacity: Optional[int],
           expected_key: Optional[str] = None) -> Optional[_Program]:
    """Assemble layout + values for one query and fetch (or compile) its
    program. ``boxes``/``windows`` arrive pow2-padded. Returns None when the
    shape doesn't qualify — the staged path is always the fallback."""
    cols = index.device.columns
    if "xf" not in cols or "yf" not in cols:
        return None
    n = int(cols["xf"].shape[0])
    bsz = int(_prune.BLOCK_SIZE)
    if n < 4 * bsz:
        return None  # tiny tables: the staged full mask is already one pass
    T = 0 if windows is None else len(windows)
    if T and ("bin" not in cols or "off" not in cols):
        return None

    layout = _Layout()
    values: list = []
    six: Dict[str, int] = {}
    six["boxes"] = layout.add(boxes.shape)
    values.append(boxes)
    six["gate"] = layout.add(gate.shape, f32=True)
    values.append(gate)
    if T:
        six["windows"] = layout.add(windows.shape)
        values.append(windows)
    try:
        res_key, emit = _lower_residual(dev_ir, sft, vocabs, set(cols),
                                        layout, values)
    except Unsupported:
        return None
    if vis is not None:
        if "__vis__" not in cols:
            return None
        six["vis"] = layout.add((len(vis),))
        values.append(vis)
        res_key = f"vis{len(vis)}&({res_key})"
    if expected_key is not None and res_key != expected_key:
        # structure drift between the lowered and interpreted residuals:
        # stay staged rather than risk a divergent program
        return None
    refine = ""
    if refine_spec is not None:
        refine, rdata = refine_spec
        six["dist" if refine == "dist" else "edges"] = layout.add(
            rdata.shape, f32=True)
        values.append(rdata)
    if grid is not None:
        six["grid"] = layout.add((4,), f32=True)
        values.append(np.asarray(grid, dtype=np.float32))

    nb = -(-n // bsz)
    cap = min(_pow2(max(4, int(np.ceil(
        nb * float(config.PRUNE_MAX_FRACTION.get()))))), _pow2(nb))
    sel_cap = min(_tier(capacity), _pow2(n)) \
        if mode in ("select", "select_refine") else 0
    unc_cap = _UNC_CAP if refine else 0
    use_pallas = refine == "pip" and _pallas_available()
    has_bin = T > 0 and "bin" in cols

    # value-free program key: geometry/time/residual VALUES ride in the
    # packed vector; only structure lands here, so N distinct bboxes of one
    # shape share one compile (the recompile-churn pin)
    key = ("fq", mode, res_key, refine, layout.signature(), n, bsz, cap,
           sel_cap, unc_cap, use_pallas, has_bin, width, height)
    slots = tuple(layout.slots)
    fn = _PROGRAMS.get(key, lambda: _jit_program(
        mode, slots, dict(six), emit, T, n, bsz, cap, sel_cap, unc_cap,
        use_pallas, has_bin, width, height, refine))
    summ = _block_summaries(index, bsz)
    return _Program(fn, cols, summ, layout.pack(values), mode, sel_cap,
                    unc_cap, n, res_key, key, layout)


# -- plan qualification -------------------------------------------------------


def _refine_spec(plan) -> Optional[Tuple[str, np.ndarray]]:
    """(kind, f32 constants) when the host residual is a single predicate
    the fused program can classify with certainty bands over a point layer:

    - ``("pip", edges)`` — point-in-polygon against a padded edge table, for
      ``Intersects`` with a POLYGON literal and for the equivalent catalog
      calls ``st_contains(POLYGON, geom)`` / ``st_intersects(geom, POLYGON)``
      (a point intersects/lies-within a polygon iff it is in the polygon);
    - ``("dist", [cx, cy, r])`` — banded radial distance, for
      ``st_distance(geom, POINT) < r`` (or ``<=``; rows within ``_DIST_BAND``
      of the circle classify uncertain, so the strictness of the comparison
      resolves in the exact host refine).

    None → the staged path serves the plan.
    """
    res = plan.residual_host
    geom_attr = getattr(plan.index, "geom", None)
    from geomesa_tpu.features import geometry as geo
    lit = None
    if isinstance(res, ir.Intersects):
        if res.attr != geom_attr:
            return None
        lit = res.geometry
    elif isinstance(res, ir.Func) and len(res.args) == 2:
        a, b = res.args
        if res.name == "st_contains":
            if isinstance(a, tuple) and b == geom_attr:
                lit = a
        elif res.name == "st_intersects":
            if isinstance(a, tuple) and b == geom_attr:
                lit = a
            elif isinstance(b, tuple) and a == geom_attr:
                lit = b
        if lit is None:
            return None
    elif isinstance(res, ir.FuncCmp) and res.name == "st_distance" \
            and res.op in ("<", "<=") and len(res.args) == 2:
        a, b = res.args
        pt = a if isinstance(a, tuple) else b if isinstance(b, tuple) else None
        attr_arg = b if isinstance(a, tuple) else a
        if pt is None or attr_arg != geom_attr or pt[0] != geo.POINT:
            return None
        r = float(res.value)
        if not r >= 0.0:
            return None
        return "dist", np.array([pt[1][0], pt[1][1], r], dtype=np.float32)
    if lit is None or lit[0] != geo.POLYGON:
        return None
    from geomesa_tpu.filter.geom_numpy import literal_segments
    edges = literal_segments(lit).astype(np.float32)
    ne = max(4, _pow2(len(edges)))
    ep = np.tile(ScanKernels._EDGE_PAD, (ne, 1))
    ep[: len(edges)] = edges
    return "pip", ep


def _from_plan(planner, plan, mode: str, capacity: Optional[int] = None,
               grid=None, width: int = 0, height: int = 0) \
        -> Optional[_Program]:
    """Qualify a staged plan for fused execution. Exactness contract: every
    decline returns None and the caller runs the staged path; every accept
    produces a program whose mask is the SAME primary/time/residual/vis
    conjunction the staged kernels evaluate."""
    if not config.FUSED_QUERY.get():
        return None
    if plan.empty or plan.index is None \
            or plan.primary_kind != "point_boxes" \
            or plan.candidate_slices is not None \
            or plan.boxes_loose is None:
        return None
    cache = getattr(plan, "_fused_cache", None)
    ck = (mode, _tier(capacity) if mode in ("select", "select_refine")
          else 0, width, height)
    if cache is not None and ck in cache:
        return cache[ck]
    boxes_geo = plan.explain.get("boxes")
    if not boxes_geo or len(boxes_geo) > len(plan.boxes_loose):
        return None
    refine_spec = None
    if mode in ("count_refine", "select_refine"):
        refine_spec = _refine_spec(plan)
        if refine_spec is None:
            return None
    elif plan.residual_host is not None:
        return None
    dev_ir = plan.explain.get("residual_device")
    vis = None
    pkey = plan.residual_device[0] if plan.residual_device else "none"
    if plan.explain.get("__vis_applied__") and pkey.startswith("vis"):
        vis = np.asarray(plan.residual_device[1][-1], dtype=np.int32)
    gate = _gate_of(boxes_geo, len(plan.boxes_loose))
    prog = _build(plan.index, planner.sft, plan.index.vocabs, mode,
                  plan.boxes_loose, gate, plan.windows, dev_ir, vis,
                  refine_spec, grid, width, height, capacity,
                  expected_key=pkey)
    try:
        if cache is None:
            cache = {}
            plan._fused_cache = cache   # plans are immutable post-build
        cache[ck] = prog
    except (AttributeError, TypeError):
        pass
    return prog


# -- execution entry points (planner integration) -----------------------------


def prepare_count_program(planner, plan) -> Optional[_Program]:
    """The PreparedQuery hook: a fused count dispatcher for a device-exact
    plan, or None (staged staging takes over)."""
    prog = _from_plan(planner, plan, "count")
    if prog is not None:
        STATS["queries"] += 1
        REGISTRY.inc("fused.queries")
    elif config.FUSED_QUERY.get():
        STATS["fallbacks"] += 1
    return prog


def try_count(planner, plan) -> Optional[int]:
    """One-dispatch count for a device-exact plan, or None."""
    prog = _from_plan(planner, plan, "count")
    if prog is None:
        if config.FUSED_QUERY.get():
            STATS["fallbacks"] += 1
        return None
    _rdl.check_current("fused_dispatch")
    STATS["queries"] += 1
    REGISTRY.inc("fused.queries")
    with _attrib.kernel("fused_count.point_boxes"):
        return int(_fetch(prog.dispatch))


def try_select(planner, plan, capacity: Optional[int]) \
        -> Optional[np.ndarray]:
    """One-dispatch select → index POSITIONS (caller maps + sorts), or None.
    Overflow regrows the capacity tier and re-dispatches (same discipline as
    scan.select)."""
    cap = capacity
    while True:
        prog = _from_plan(planner, plan, "select", capacity=cap)
        if prog is None:
            if config.FUSED_QUERY.get():
                STATS["fallbacks"] += 1
            return None
        _rdl.check_current("fused_dispatch")
        STATS["queries"] += 1
        REGISTRY.inc("fused.queries")
        with _attrib.kernel("fused_select.point_boxes", prog.sel_cap):
            out = np.asarray(_fetch(prog.dispatch))
        cnt = int(out[0])
        if cnt <= prog.sel_cap:
            return out[1: 1 + cnt].astype(np.int64)
        STATS["overflow_retries"] += 1
        cap = _pow2(cnt)


def try_count_refine(planner, plan) -> Optional[int]:
    """Fused scan + certainty-band polygon refine + count in one dispatch;
    only the uncertain sliver re-evaluates on host in exact f64. None when
    the shape doesn't qualify or uncertainty overflowed."""
    prog = _from_plan(planner, plan, "count_refine")
    if prog is None:
        if config.FUSED_QUERY.get():
            STATS["fallbacks"] += 1
        return None
    _rdl.check_current("fused_dispatch")
    STATS["queries"] += 1
    REGISTRY.inc("fused.queries")
    with _attrib.kernel("fused_count_refine.point_boxes"):
        out = np.asarray(_fetch(prog.dispatch))
    certain, n_unc = int(out[0]), int(out[1])
    if n_unc > prog.unc_cap:
        return None  # uncertainty overflow: staged/host refine instead
    if n_unc == 0:
        # the refine stage ran in-kernel (its time is in device_wait);
        # keep the stage visible in the trace contract with 0 host rows
        if _trace.enabled():
            _trace.record("refine", "refine", 0.0)
        return certain
    pos = out[2: 2 + n_unc].astype(np.int64)
    rows = plan.index.map_rows(pos)
    from geomesa_tpu.filter.evaluate import evaluate_at
    with _trace.span("refine", kind="refine", rows=len(rows)):
        return certain + int(np.sum(
            evaluate_at(plan.residual_host, planner.table, rows)))


def try_select_refine(planner, plan, capacity: Optional[int]) \
        -> Optional[np.ndarray]:
    """Fused select with in-kernel polygon refine → FINAL sorted table rows
    (certain hits + host-confirmed uncertain rows), or None."""
    cap = capacity
    while True:
        prog = _from_plan(planner, plan, "select_refine", capacity=cap)
        if prog is None:
            if config.FUSED_QUERY.get():
                STATS["fallbacks"] += 1
            return None
        _rdl.check_current("fused_dispatch")
        STATS["queries"] += 1
        REGISTRY.inc("fused.queries")
        with _attrib.kernel("fused_select_refine.point_boxes", prog.sel_cap):
            out = np.asarray(_fetch(prog.dispatch))
        n_in, n_unc = int(out[0]), int(out[1])
        if n_unc > prog.unc_cap:
            return None
        if n_in > prog.sel_cap:
            STATS["overflow_retries"] += 1
            cap = _pow2(n_in)
            continue
        in_pos = out[2: 2 + n_in].astype(np.int64)
        rows = plan.index.map_rows(in_pos)
        if n_unc:
            unc_pos = out[2 + prog.sel_cap:
                          2 + prog.sel_cap + n_unc].astype(np.int64)
            unc_rows = plan.index.map_rows(unc_pos)
            from geomesa_tpu.filter.evaluate import evaluate_at
            with _trace.span("refine", kind="refine", rows=len(unc_rows)):
                keep = evaluate_at(plan.residual_host, planner.table,
                                   unc_rows)
            rows = np.concatenate([rows, unc_rows[keep]])
        elif _trace.enabled():
            # in-kernel refine resolved every candidate: the stage's time
            # is inside device_wait, but it must stay a visible stage
            _trace.record("refine", "refine", 0.0)
        return np.sort(rows)


def try_density(planner, plan, grid_bbox, width: int, height: int):
    """One-dispatch heat-map: ((H, W) f32 grid, count) or None. Available to
    aggregation callers; the staged density kernels remain the default."""
    prog = _from_plan(planner, plan, "density", grid=grid_bbox, width=width,
                      height=height)
    if prog is None:
        return None
    _rdl.check_current("fused_dispatch")
    STATS["queries"] += 1
    REGISTRY.inc("fused.queries")
    with _attrib.kernel("fused_density.point_boxes"):
        grid, cnt = _fetch(prog.dispatch)
    return np.asarray(grid), int(cnt)


# -- union (Or-of-covers) lowering --------------------------------------------


def _jit_union_program(mode: str, slots: tuple, branches: tuple,
                       six_g: Dict[str, int], n: int, bsz: int, cap: int,
                       sel_cap: int, has_bin: bool, width: int, height: int):
    """One device program for an OR-of-covers plan: per-branch primary/time/
    residual/vis masks OR *inside* the program (dedup is inherent — the OR is
    one mask), so a union select or density render is ONE dispatch instead of
    per-branch scans + host row-set union. ``branches`` is a tuple of
    (slot-index dict, residual emit | None, window count) from
    ``_build_union``; the block gate keeps a block alive when ANY branch's
    envelope set touches it."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    get = _make_get(slots)
    total = cap * bsz

    def run(cols, summ, packed):
        alive = jnp.zeros(summ["bxmin"].shape[0], dtype=bool)
        for six, _, T in branches:
            gate = get(packed, six["gate"])
            a = jnp.any(
                (summ["bxmax"][:, None] >= gate[None, :, 0])
                & (summ["bxmin"][:, None] <= gate[None, :, 2])
                & (summ["bymax"][:, None] >= gate[None, :, 1])
                & (summ["bymin"][:, None] <= gate[None, :, 3]), axis=1)
            if T and has_bin:
                windows = get(packed, six["windows"])
                blo, bhi = windows[:, 0], windows[:, 2]
                a = a & jnp.any(
                    (blo <= bhi)[None, :]
                    & (summ["binmin"][:, None] <= bhi[None, :])
                    & (summ["binmax"][:, None] >= blo[None, :]), axis=1)
            alive = alive | a
        n_alive = jnp.sum(alive)

        def mask_of(c, membership=None):
            m = None
            for six, emit, T in branches:
                bm = PRIMARY_FNS["point_boxes"](c, get(packed, six["boxes"]))
                if T:
                    bm = bm & _time_mask(c, get(packed, six["windows"]))
                if emit is not None:
                    bm = bm & emit(c, packed, get)
                if "vis" in six:
                    codes = get(packed, six["vis"])
                    bm = bm & jnp.any(
                        c["__vis__"][:, None] == codes[None, :], axis=1)
                m = bm if m is None else (m | bm)
            if "__valid__" in c:
                m = m & c["__valid__"]
            if membership is not None:
                m = m & membership
            return m

        def gathered():
            bids = jnp.nonzero(
                alive, size=cap, fill_value=-1)[0].astype(jnp.int32)
            bids = jnp.where(bids < nb_blocks, bids, -1)
            starts = bids * bsz
            astart = jnp.clip(starts, 0, max(0, n - bsz))
            rows = astart[:, None] + jnp.arange(bsz, dtype=jnp.int32)[None, :]
            membership = ((bids >= 0)[:, None]
                          & (rows >= starts[:, None])
                          & (rows < starts[:, None] + bsz)).reshape(-1)
            g = _LazyBlockGather(cols, astart, bsz, total)
            return mask_of(g, membership), rows.reshape(-1), g

        if mode == "select":
            def pruned(_):
                m, rowids, _ = gathered()
                sel = jnp.nonzero(m, size=sel_cap, fill_value=total)[0]
                rows = jnp.where(
                    sel < total, rowids[jnp.clip(sel, 0, total - 1)], n)
                return jnp.concatenate([
                    jnp.sum(m)[None].astype(jnp.int32),
                    rows.astype(jnp.int32)])

            def full(_):
                m = mask_of(cols)
                sel = jnp.nonzero(m, size=sel_cap, fill_value=n)[0]
                return jnp.concatenate([
                    jnp.sum(m)[None].astype(jnp.int32),
                    sel.astype(jnp.int32)])

            return lax.cond(n_alive <= cap, pruned, full, 0)

        if mode == "density":
            grid = get(packed, six_g["grid"])

            def pruned(_):
                m, _, g = gathered()
                return (_grid_scatter(g["xf"], g["yf"], m, None, grid,
                                      width, height),
                        jnp.sum(m).astype(jnp.int32))

            def full(_):
                m = mask_of(cols)
                return (_grid_scatter(cols["xf"], cols["yf"], m, None, grid,
                                      width, height),
                        jnp.sum(m).astype(jnp.int32))

            return lax.cond(n_alive <= cap, pruned, full, 0)

        raise ValueError(mode)

    nb_blocks = -(-n // bsz)
    STATS["programs_built"] += 1
    jitted = jax.jit(run)
    if _attrib.enabled():
        jitted = _attrib.compile_probe(jitted, f"fused_union_{mode}", cap)
    return jitted


def _build_union(planner, plan, mode: str, auths,
                 capacity: Optional[int] = None, grid=None, width: int = 0,
                 height: int = 0) -> Optional[_Program]:
    """Qualify an OR-of-covers (UnionScanPlan) for single-dispatch execution:
    every branch must be a device-exact point_boxes scan on ONE shared index
    (the same precondition as the fused OR-of-masks count). Auths fold
    per-branch exactly as the staged union path does — vis code sets ride the
    packed vector. Any decline returns None and the per-branch staged path
    serves the query."""
    if not config.FUSED_QUERY.get():
        return None
    idx = plan.same_index_device_exact()
    if idx is None:
        return None
    cols = idx.device.columns
    if "xf" not in cols or "yf" not in cols:
        return None
    n = int(cols["xf"].shape[0])
    bsz = int(_prune.BLOCK_SIZE)
    if n < 4 * bsz:
        return None
    layout = _Layout()
    values: list = []
    branches: list = []
    res_keys: list = []
    for _, bp in plan.branches:
        bp = planner._apply_auths(bp, auths)
        if bp.empty:
            continue  # auths folded this branch to nothing
        if bp.primary_kind != "point_boxes" \
                or bp.candidate_slices is not None \
                or bp.boxes_loose is None or bp.residual_host is not None:
            return None
        boxes_geo = bp.explain.get("boxes")
        if not boxes_geo or len(boxes_geo) > len(bp.boxes_loose):
            return None
        T = 0 if bp.windows is None else len(bp.windows)
        if T and ("bin" not in cols or "off" not in cols):
            return None
        six: Dict[str, int] = {}
        six["boxes"] = layout.add(bp.boxes_loose.shape)
        values.append(bp.boxes_loose)
        gate = _gate_of(boxes_geo, len(bp.boxes_loose))
        six["gate"] = layout.add(gate.shape, f32=True)
        values.append(gate)
        if T:
            six["windows"] = layout.add(bp.windows.shape)
            values.append(bp.windows)
        dev_ir = bp.explain.get("residual_device")
        try:
            res_key, emit = _lower_residual(dev_ir, planner.sft, idx.vocabs,
                                            set(cols), layout, values)
        except Unsupported:
            return None
        pkey = bp.residual_device[0] if bp.residual_device else "none"
        if bp.explain.get("__vis_applied__") and pkey.startswith("vis"):
            vis = np.asarray(bp.residual_device[1][-1], dtype=np.int32)
            six["vis"] = layout.add((len(vis),))
            values.append(vis)
            res_key = f"vis{len(vis)}&({res_key})"
        if res_key != pkey:
            return None   # lowered/interpreted drift: stay staged
        branches.append((six, emit, T))
        res_keys.append(f"{res_key}|b{len(bp.boxes_loose)}w{T}"
                        + ("v" if "vis" in six else ""))
    if not branches:
        return None
    six_g: Dict[str, int] = {}
    if grid is not None:
        six_g["grid"] = layout.add((4,), f32=True)
        values.append(np.asarray(grid, dtype=np.float32))
    nb = -(-n // bsz)
    cap = min(_pow2(max(4, int(np.ceil(
        nb * float(config.PRUNE_MAX_FRACTION.get()))))), _pow2(nb))
    sel_cap = min(_tier(capacity), _pow2(n)) if mode == "select" else 0
    has_bin = "bin" in cols

    key = ("fqu", mode, tuple(res_keys), layout.signature(), n, bsz, cap,
           sel_cap, has_bin, width, height)
    slots = tuple(layout.slots)
    bspec = tuple(branches)
    fn = _PROGRAMS.get(key, lambda: _jit_union_program(
        mode, slots, bspec, dict(six_g), n, bsz, cap, sel_cap, has_bin,
        width, height))
    summ = _block_summaries(idx, bsz)
    return _Program(fn, cols, summ, layout.pack(values), mode, sel_cap,
                    0, n, "|".join(res_keys), key, layout)


def try_union_select(planner, plan, auths,
                     capacity: Optional[int] = None) -> Optional[np.ndarray]:
    """One-dispatch select for an OR-of-covers plan → FINAL sorted table
    rows (branch overlaps dedup in the in-program OR), or None (per-branch
    scans + host union serve instead)."""
    cap = capacity
    while True:
        prog = _build_union(planner, plan, "select", auths, capacity=cap)
        if prog is None:
            if config.FUSED_QUERY.get():
                STATS["fallbacks"] += 1
            return None
        _rdl.check_current("fused_dispatch")
        STATS["queries"] += 1
        REGISTRY.inc("fused.queries")
        with _attrib.kernel("fused_union_select", prog.sel_cap):
            out = np.asarray(_fetch(prog.dispatch))
        cnt = int(out[0])
        if cnt <= prog.sel_cap:
            pos = out[1: 1 + cnt].astype(np.int64)
            idx = plan.same_index_device_exact()
            return np.sort(idx.map_rows(pos))
        STATS["overflow_retries"] += 1
        cap = _pow2(cnt)


def try_union_density(planner, plan, auths, grid_bbox, width: int,
                      height: int):
    """One-dispatch union heat-map: ((H, W) f32 grid, count) or None."""
    prog = _build_union(planner, plan, "density", auths, grid=grid_bbox,
                        width=width, height=height)
    if prog is None:
        if config.FUSED_QUERY.get():
            STATS["fallbacks"] += 1
        return None
    _rdl.check_current("fused_dispatch")
    STATS["queries"] += 1
    REGISTRY.inc("fused.queries")
    with _attrib.kernel("fused_union_density"):
        grid, cnt = _fetch(prog.dispatch)
    return np.asarray(grid), int(cnt)


# -- shape-keyed recipe fast path (skip planning entirely) --------------------


def _shape_key(f: ir.Filter) -> str:
    """Value-free structural signature of a filter tree — the same
    normalization discipline the scheduler's plan cache uses: two queries
    with this key in common differ only in geometry/time/constant VALUES."""
    if isinstance(f, ir.And):
        return "and(" + ",".join(_shape_key(c) for c in f.children) + ")"
    if isinstance(f, ir.Or):
        return "or(" + ",".join(_shape_key(c) for c in f.children) + ")"
    if isinstance(f, ir.Not):
        return f"not({_shape_key(f.child)})"
    if isinstance(f, ir.Include):
        return "inc"
    if isinstance(f, ir.Exclude):
        return "exc"
    if isinstance(f, ir.BBox):
        return f"bbox:{f.attr}"
    if isinstance(f, ir.Intersects):
        return f"ints:{f.attr}:{f.geometry[0]}"
    if isinstance(f, ir.During):
        return f"during:{f.attr}:{int(f.lo_inclusive)}{int(f.hi_inclusive)}"
    if isinstance(f, ir.Cmp):
        return f"cmp{f.op}:{f.attr}"
    if isinstance(f, ir.In):
        return f"in{_pow2(len(f.values))}:{f.attr}"
    if isinstance(f, ir.Func):
        return f"fn:{f.name}({_func_args_sig(f.args)})"
    if isinstance(f, ir.FuncCmp):
        return f"fc{f.op}:{f.name}({_func_args_sig(f.args)})"
    raise Unsupported(type(f).__name__)


def _func_args_sig(args: tuple) -> str:
    """Value-free signature of st_* call arguments: attributes by name,
    geometry literals by type code, scalars as 'f' — two calls with this
    signature in common differ only in literal VALUES, the same normalization
    the rest of the shape key uses."""
    parts = []
    for a in args:
        if isinstance(a, str):
            parts.append(f"a:{a}")
        elif isinstance(a, tuple):
            parts.append(f"l{a[0]}")
        elif isinstance(a, ir.FuncExpr):
            parts.append(f"{a.name}({_func_args_sig(a.args)})")
        else:
            parts.append("f")
    return ",".join(parts)


def _auths_key(auths) -> Optional[tuple]:
    return None if auths is None else tuple(sorted(auths))


class _RecipeCache:
    """Small thread-safe LRU for (shape, auths) → Recipe | None (negative).
    Deliberately self-contained — the recipe lookup sits ahead of planning
    on the hottest path and must stay a dict op under one lock."""

    MISS = object()

    def __init__(self):
        self._d: "OrderedDict" = OrderedDict()
        self._lock = threading.Lock()

    def get(self, key):
        with self._lock:
            v = self._d.get(key, self.MISS)
            if v is not self.MISS:
                self._d.move_to_end(key)
            return v

    def put(self, key, value) -> None:
        with self._lock:
            cap = max(1, int(config.FUSED_SHAPE_CACHE.get()))
            self._d[key] = value
            self._d.move_to_end(key)
            while len(self._d) > cap:
                self._d.popitem(last=False)

    def __len__(self) -> int:
        return len(self._d)


def _recipes(planner) -> _RecipeCache:
    cache = getattr(planner, "_fused_recipes", None)
    if cache is None:
        cache = _RecipeCache()
        planner._fused_recipes = cache
    return cache


_EMPTY_BIND = object()   # bind result: provably-empty query (count 0)


def _boxes_fp62_fast(boxes) -> Optional[np.ndarray]:
    """Scalar twin of ``spatial._boxes_fp62`` for the handful-of-boxes case:
    pure-python IEEE-754 math (bit-identical to the numpy path — python
    floats ARE C doubles, and floor(ldexp(frac, 62)) of an integral float
    converts to int exactly) without ~40µs of small-array numpy dispatch.
    None on anything unusual (NaN coordinates) → caller uses the array path.
    """
    import math
    out = np.empty((len(boxes), 8), dtype=np.int32)
    m62 = (1 << 62) - 1
    m31 = (1 << 31) - 1
    try:
        for i, (xmin, ymin, xmax, ymax) in enumerate(boxes):
            row = out[i]
            for j, (c, lo, hi) in enumerate(
                    ((xmin, -180.0, 360.0), (xmax, -180.0, 360.0),
                     (ymin, -90.0, 180.0), (ymax, -90.0, 180.0))):
                frac = (float(c) - lo) / hi
                frac = 0.0 if frac < 0.0 else (1.0 if frac > 1.0 else frac)
                v = min(math.floor(math.ldexp(frac, 62)), m62)
                row[2 * j] = v >> 31
                row[2 * j + 1] = v & m31
    except (ValueError, OverflowError):   # NaN / inf coordinate
        return None
    return out


def _collect_values(f: Optional[ir.Filter], sft, string_vocabs,
                    out: list) -> None:
    """Value-collecting twin of ``_lower_residual``'s walk: appends this
    query's residual constants to ``out`` in the SAME traversal order the
    lowering allocated its layout slots, raising ``Unsupported`` under the
    same conditions. Used by the template rebind (``_rebind``), which then
    shape-checks every value against the template's slots — any drift falls
    back to the full ``_build``."""
    if f is None:
        return
    if isinstance(f, (ir.Include, ir.Exclude)):
        return
    if isinstance(f, (ir.And, ir.Or)):
        for c in f.children:
            _collect_values(c, sft, string_vocabs, out)
        return
    if isinstance(f, ir.Not):
        _collect_values(f.child, sft, string_vocabs, out)
        return
    if isinstance(f, ir.Cmp):
        attr = sft.attribute(f.attr)
        if attr.type_name == "String":
            vocab = string_vocabs.get(f.attr)
            if vocab is None:
                raise Unsupported("no vocab")
            try:
                out.append(vocab.index(f.value))
            except ValueError:
                out.append(-1)
            return
        if attr.type_name not in _EXACT_DEVICE_TYPES:
            raise Unsupported("inexact cmp")
        out.append(f.value)
        return
    if isinstance(f, ir.In):
        attr = sft.attribute(f.attr)
        if attr.type_name == "String":
            vocab = string_vocabs.get(f.attr)
            if vocab is None:
                raise Unsupported("no vocab")
            codes = [vocab.index(v) for v in f.values if v in vocab] or [-1]
        elif attr.type_name in ("Int", "Integer"):
            codes = [int(v) for v in f.values]
        else:
            raise Unsupported("IN on non-int/string")
        size = max(1, 1 << (len(codes) - 1).bit_length())
        out.append(codes + [codes[-1]] * (size - len(codes)))
        return
    raise Unsupported(type(f).__name__)


def _rebind(recipe, boxes, gate, windows, dev_ir) -> Optional[_Program]:
    """Hot rebind: pack this query's values straight into the recipe's
    template program — no layout rebuild, no lowering, no cache lookups.
    Every value is size-checked against its template slot; any mismatch
    (vocab-miss IN shrank its pad, a column group reload, table growth)
    returns None and the ordinary ``_build`` re-derives everything."""
    tmpl = recipe.tmpl
    prog, layout = tmpl
    cols = recipe.index.device.columns
    if cols is not prog.cols:
        recipe.tmpl = None   # device table reloaded: template is stale
        return None
    values = [boxes, gate]
    if windows is not None:
        values.append(windows)
    try:
        _collect_values(dev_ir, recipe.sft, recipe.vocabs, values)
    except Unsupported:
        return None
    if recipe.vis is not None:
        values.append(recipe.vis)
    slots = layout.slots
    if len(values) != len(slots):
        return None
    packed = np.zeros(layout.padded, dtype=np.int32)
    for (off, size, shape, f32), v in zip(slots, values):
        if f32:
            a = np.ascontiguousarray(v, dtype=np.float32).reshape(-1)
            if a.size != size:
                return None
            packed[off:off + size] = a.view(np.int32)
        else:
            a = np.asarray(v, dtype=np.int32).reshape(-1)
            if a.size != size:
                return None
            packed[off:off + size] = a
    return _Program(prog.fn, cols, prog.summ, packed, prog.mode,
                    prog.sel_cap, prog.unc_cap, prog.n, prog.res_key,
                    prog.key)


class Recipe:
    """Bind instructions for one (filter shape, auths): everything needed to
    turn a NEW same-shape filter into a packed fused count dispatch without
    touching ``planner.plan()`` — extract boxes/intervals, window them,
    re-lower the residual (values only; the structure key must reproduce),
    pack, go. Any drift (box count, window count, residual key, host
    residual appearing) returns None and the slow path serves the query
    exactly."""

    __slots__ = ("index", "sft", "geom", "dtg", "period", "vocabs",
                 "n_boxes", "n_windows", "res_key", "vis", "template_plan",
                 "tmpl")

    def __init__(self, plan, planner, res_key, vis):
        self.tmpl = None   # (program, layout) after the first full _build
        self.index = plan.index
        self.sft = planner.sft
        self.geom = plan.index.geom
        self.dtg = plan.index.dtg
        self.period = plan.index.period
        self.vocabs = plan.index.vocabs
        self.n_boxes = len(plan.boxes_loose)
        self.n_windows = 0 if plan.windows is None else len(plan.windows)
        self.res_key = res_key
        self.vis = vis
        self.template_plan = plan

    def bind(self, f: ir.Filter):
        """→ (boxes, gate, windows, dev_ir) | _EMPTY_BIND | None."""
        if self.geom is None:
            return None
        ext = extract_bboxes(f, self.geom)
        if len(ext.boxes) == 0:
            return _EMPTY_BIND
        if ext.unconstrained:
            return None
        boxes = (_boxes_fp62_fast(ext.boxes) if len(ext.boxes) <= 4
                 else None)
        if boxes is None:
            boxes = _boxes_fp62(ext.boxes)
        if len(boxes) & (len(boxes) - 1):
            boxes = pad_boxes(boxes)
        if len(boxes) != self.n_boxes:
            return None
        windows = None
        iv = extract_intervals(f, self.dtg) if self.dtg else None
        if iv is not None and len(iv.intervals) == 0:
            return _EMPTY_BIND
        if iv is not None and not iv.unconstrained:
            w = np.empty((len(iv.intervals), 4), dtype=np.int32)
            i32 = (1 << 31) - 1   # open-ended intervals overflow the bin i32
            for i, (lo, hi) in enumerate(iv.intervals):
                blo, olo = time_to_binned_time(lo, self.period)
                bhi, ohi = time_to_binned_time(hi, self.period)
                w[i] = (max(-i32, int(blo)), int(olo),
                        min(i32, int(bhi)), int(ohi))
            windows = pad_windows(w)
        if (0 if windows is None else len(windows)) != self.n_windows:
            return None
        residual = _strip_handled(f, self.geom, self.dtg, True)
        dev_ir, host_ir = split_residual(
            residual, self.sft, self.vocabs, set(self.index.device.columns))
        if host_ir is not None:
            return None   # refine shapes go through the planner
        return boxes, _gate_of(ext.boxes, len(boxes)), windows, dev_ir


class FusedPrepared:
    """PreparedQuery-shaped handle from the recipe fast path: the query went
    filter → packed constants → one dispatch, never through
    ``planner.plan()``. ``plan`` exposes the recipe's template plan (its
    box/window VALUES belong to the recipe's exemplar query — audit and
    explain surfaces only)."""

    def __init__(self, planner, recipe: Recipe, f: ir.Filter, auths,
                 prog: Optional[_Program]):
        self.planner = planner
        self.plan = recipe.template_plan
        self.filter = f
        self.auths = auths
        self._prog = prog        # None → provably empty

    @property
    def device_exact(self) -> bool:
        return self._prog is not None

    def count_async(self):
        """Async dispatch → 0-d device array (None for empty binds) — the
        same pipelining contract as PreparedQuery.count_async."""
        if self._prog is None:
            return None
        with _trace.span("device_scan", kind="device_scan"):
            return self._prog.dispatch()

    def count(self) -> int:
        from geomesa_tpu.index.guards import Deadline
        attrs = {"type": self.planner.sft.name, "prepared": True}
        if _trace.enabled():
            attrs["filter"] = str(self.filter)  # ir repr is µs-scale; only
        with _trace.trace("count", **attrs):    # pay it when traces record
            dl = Deadline(self.planner.timeout_ms)
            t0 = time.perf_counter()
            n = 0 if self._prog is None else int(_fetch(self._prog.dispatch))
            dl.check("scan")
            self.planner._write_audit(self.plan, self.filter, 0.0,
                                      (time.perf_counter() - t0) * 1000, n)
            return n

    def select_indices(self) -> np.ndarray:
        # selects replan through the general path (capacity tiers vary);
        # counts are the latency-critical shape the recipe accelerates
        return self.planner.select_indices(self.filter, auths=self.auths)


def fast_prepare(planner, f: ir.Filter, auths) -> Optional[FusedPrepared]:
    """Recipe-keyed prepare: when this (filter shape, auths) has fused
    before, bind the new VALUES straight into the compiled program — no
    parse, no plan, no range decomposition, one dispatch. None sends the
    caller down the ordinary prepare path (which registers the shape)."""
    if not config.FUSED_QUERY.get() or getattr(planner, "interceptors", None):
        return None
    try:
        skey = _shape_key(f)
    except Unsupported:
        return None
    cache = _recipes(planner)
    r = cache.get((skey, _auths_key(auths)))
    if r is _RecipeCache.MISS:
        STATS["shape_misses"] += 1
        return None
    if r is None:   # negative entry: shape known non-fusable
        return None
    bound = r.bind(f)
    if bound is _EMPTY_BIND:
        STATS["shape_hits"] += 1
        return FusedPrepared(planner, r, f, auths, None)
    if bound is None:
        STATS["bind_failures"] += 1
        return None
    boxes, gate, windows, dev_ir = bound
    prog = _rebind(r, boxes, gate, windows, dev_ir) \
        if r.tmpl is not None else None
    if prog is None:
        prog = _build(r.index, r.sft, r.vocabs, "count", boxes, gate,
                      windows, dev_ir, r.vis, None, None, 0, 0, None,
                      expected_key=r.res_key)
        if prog is None:
            STATS["bind_failures"] += 1
            return None
        if prog.layout is not None:
            r.tmpl = (prog, prog.layout)
    STATS["shape_hits"] += 1
    STATS["queries"] += 1
    REGISTRY.inc("fused.shape_hits")
    REGISTRY.inc("fused.queries")
    return FusedPrepared(planner, r, f, auths, prog)


def note_shape(planner, plan, f: ir.Filter, auths,
               prog: Optional[_Program]) -> None:
    """Slow-path epilogue: record how this shape resolved so the NEXT
    same-shape query takes the recipe fast path (or skips the attempt —
    negative entries stop re-qualifying known-staged shapes)."""
    if not config.FUSED_QUERY.get() or getattr(planner, "interceptors", None):
        return
    if getattr(plan, "empty", False):
        return   # emptiness is a property of the values, not the shape
    try:
        skey = _shape_key(f)
    except Unsupported:
        return
    cache = _recipes(planner)
    ck = (skey, _auths_key(auths))
    if cache.get(ck) is not _RecipeCache.MISS:
        return
    if prog is None:
        cache.put(ck, None)
        return
    vis = None
    pkey = plan.residual_device[0] if plan.residual_device else "none"
    if plan.explain.get("__vis_applied__") and pkey.startswith("vis"):
        vis = np.asarray(plan.residual_device[1][-1], dtype=np.int32)
    cache.put(ck, Recipe(plan, planner, prog.res_key, vis))


# -- startup warming ----------------------------------------------------------


def warm_programs(index) -> int:
    """Compile the common fused single-dispatch count shapes for an index
    ahead of traffic (1 box; 1 box + 1 window on temporal layers) and run
    each once, paying the XLA compile + packed transfer-shape setup at
    startup instead of on the first cold query. Returns programs warmed."""
    if not config.FUSED_QUERY.get():
        return 0
    cols = getattr(getattr(index, "device", None), "columns", None)
    if not cols or "xf" not in cols:
        return 0
    if not getattr(index, "points", False):
        return 0
    n = int(cols["xf"].shape[0])
    if n < 4 * int(_prune.BLOCK_SIZE):
        return 0
    warmed = 0
    shapes = [(1, 0)]
    if "bin" in cols and "off" in cols:
        shapes.append((1, 1))
    for nb, nw in shapes:
        boxes = pad_boxes(np.empty((0, 8), dtype=np.int32), min_size=nb)
        windows = pad_windows(np.empty((0, 4), dtype=np.int32),
                              min_size=nw) if nw else None
        prog = _build(index, index.sft, index.vocabs, "count", boxes,
                      _gate_of((), len(boxes)), windows, None, None, None,
                      None, 0, 0, None)
        if prog is None:
            continue
        _fetch(prog.dispatch)   # empty gate: executes, compiles both branches
        warmed += 1
    return warmed


def stats_snapshot() -> Dict[str, int]:
    """STATS + live program count (debug/healthz surfaces)."""
    out = dict(STATS)
    out["programs"] = len(_PROGRAMS._jitted)
    return out
