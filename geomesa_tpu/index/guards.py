"""Query interceptors, guards, audit, timeouts.

≙ reference planning/QueryInterceptor.scala:28 (SPI hooks that rewrite or
veto queries), guard/GraduatedQueryGuard.scala + TemporalQueryGuard,
QueryProperties.BlockFullTableScans (conf/QueryProperties.scala:40), the
audit trail (audit/QueryEvent.scala:13 via AuditWriter), and the
ThreadManagement QueryKiller (index/utils/ThreadManagement.scala:28).

Timeout semantics: XLA dispatches are uninterruptible, so the deadline is
checked between pipeline stages (plan → scan → refine) — the same guarantee
level as the reference's cooperative QueryKiller, which also only interrupts
between iterator batches.
"""

from __future__ import annotations

import json
import time
from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple

from geomesa_tpu.filter import ir
from geomesa_tpu.filter.extract import extract_bboxes, extract_intervals


class QueryGuardError(Exception):
    """A guard vetoed the query (≙ the IllegalArgumentException the
    reference guards raise)."""


class QueryTimeout(Exception):
    """Deadline exceeded (≙ ThreadManagement.QueryKiller cancellation)."""


class QueryInterceptor:
    """Rewrite and/or veto hook (≙ QueryInterceptor SPI)."""

    def rewrite(self, f: ir.Filter, sft) -> ir.Filter:
        return f

    def guard(self, plan, f: ir.Filter, sft) -> Optional[str]:
        """Return an error message to veto, None to allow."""
        return None


class FullTableScanGuard(QueryInterceptor):
    """Block filtered queries that degenerate to a full-table scan
    (≙ geomesa.scan.block-full-table)."""

    def guard(self, plan, f, sft):
        if isinstance(f, ir.Include):
            return None  # explicit full reads are allowed, as in the reference
        if plan.empty or plan.candidate_slices is not None:
            return None
        if plan.primary_kind == "none" and plan.windows is None:
            return ("Query would require a full-table scan "
                    "(no index-serviceable predicate); add a spatial, "
                    "temporal, or indexed-attribute constraint")
        return None


class TemporalQueryGuard(QueryInterceptor):
    """Require a bounded temporal filter under ``max_duration_ms``
    (≙ guard/TemporalQueryGuard)."""

    def __init__(self, max_duration_ms: int):
        self.max_duration_ms = int(max_duration_ms)

    def guard(self, plan, f, sft):
        dtg = sft.dtg_attribute
        if dtg is None or plan.empty:
            return None
        iv = extract_intervals(f, dtg.name)
        if iv is None or iv.unconstrained or not len(iv.intervals):
            return f"Query requires a temporal filter on {dtg.name!r}"
        span = max(int(hi) - int(lo) for lo, hi in iv.intervals)
        if span > self.max_duration_ms:
            return (f"Temporal filter spans {span}ms, over the "
                    f"{self.max_duration_ms}ms limit")
        return None


@dataclass
class SizeAndDuration:
    """One graduated limit: queries within ``area_deg2`` may span up to
    ``duration_ms`` (≙ GraduatedQueryGuard.SizeAndDuration)."""
    area_deg2: float
    duration_ms: int


class GraduatedQueryGuard(QueryInterceptor):
    """Smaller spatial extent ⇒ longer allowed duration (≙
    guard/GraduatedQueryGuard.scala). Limits sorted by area ascending; the
    first limit whose area covers the query applies; the final limit may use
    area=inf as the catch-all."""

    def __init__(self, limits: Sequence[SizeAndDuration]):
        self.limits = sorted(limits, key=lambda l: l.area_deg2)

    def guard(self, plan, f, sft):
        geom = sft.geometry_attribute
        dtg = sft.dtg_attribute
        if geom is None or plan.empty:
            return None
        ext = extract_bboxes(f, geom.name)
        area = 360.0 * 180.0 if ext.unconstrained else sum(
            max(0.0, (x1 - x0)) * max(0.0, (y1 - y0))
            for x0, y0, x1, y1 in ext.boxes)
        limit = next((l for l in self.limits if area <= l.area_deg2), None)
        if limit is None:
            return (f"Query area {area:.1f}deg2 exceeds the largest "
                    f"configured limit")
        if dtg is None:
            return None
        iv = extract_intervals(f, dtg.name)
        if iv is None or iv.unconstrained or not len(iv.intervals):
            span = None
        else:
            span = max(int(hi) - int(lo) for lo, hi in iv.intervals)
        if span is None or span > limit.duration_ms:
            return (f"Queries covering {area:.1f}deg2 must include a "
                    f"temporal filter of at most {limit.duration_ms}ms")
        return None


# -- audit (≙ audit/QueryEvent + AuditWriter) --------------------------------


@dataclass
class QueryEvent:
    type_name: str
    filter: str
    user: str = ""
    ts_ms: int = 0
    plan_time_ms: float = 0.0
    scan_time_ms: float = 0.0
    hits: int = 0
    index: str = ""

    def to_dict(self) -> dict:
        return self.__dict__.copy()


class AuditWriter:
    """In-memory audit trail with optional JSONL sink (≙ AuditLogger /
    the Accumulo ``_queries`` table).

    The JSONL path is bounded against unbounded growth: with ``max_bytes``
    set, the file rotates (keep-one-previous: ``path`` → ``path.1``) before
    an append would cross the limit, and events lost when a rotation
    discards the old ``.1`` file land on the ``audit.dropped`` counter —
    total on-disk footprint stays <= ~2*max_bytes."""

    def __init__(self, path: Optional[str] = None, keep: int = 1000,
                 max_bytes: Optional[int] = None):
        import os
        import threading
        self.path = path
        self.keep = keep
        self.max_bytes = int(max_bytes) if max_bytes else None
        self.events: List[QueryEvent] = []
        self._lock = threading.Lock()
        self._size = os.path.getsize(path) if path and os.path.exists(path) \
            else 0
        self._file_events: Optional[int] = 0 if self._size == 0 else None
        self._prev_events: Optional[int] = None  # events in path.1

    @staticmethod
    def _count_lines(path: str) -> int:
        try:
            with open(path, "rb") as fh:
                return sum(chunk.count(b"\n")
                           for chunk in iter(lambda: fh.read(1 << 20), b""))
        except OSError:
            return 0

    def _rotate(self) -> None:
        # the keep-N shuffle itself is the shared, tested helper the WAL
        # segments and snapshot pruning also use (durability/rotation.py);
        # only the event accounting is audit-specific
        from geomesa_tpu.durability.rotation import rotate

        def _account_drop(dropped_path: str) -> None:
            dropped = self._prev_events if self._prev_events is not None \
                else self._count_lines(dropped_path)
            if dropped:
                from geomesa_tpu.metrics import REGISTRY
                REGISTRY.inc("audit.dropped", dropped)

        rotate(self.path, keep=1, on_drop=_account_drop)
        self._prev_events = self._file_events \
            if self._file_events is not None \
            else self._count_lines(self.path + ".1")
        self._size = 0
        self._file_events = 0

    def write(self, event: QueryEvent) -> None:
        with self._lock:
            self.events.append(event)
            if len(self.events) > self.keep:
                self.events = self.events[-self.keep:]
            if not self.path:
                return
            line = json.dumps(event.to_dict()) + "\n"
            if (self.max_bytes is not None and self._size > 0
                    and self._size + len(line) > self.max_bytes):
                self._rotate()
            with open(self.path, "a") as fh:
                fh.write(line)
            self._size += len(line)
            if self._file_events is not None:
                self._file_events += 1


# -- deadline ----------------------------------------------------------------


class Deadline:
    """Cooperative deadline checked between pipeline stages. Also honors
    the ambient per-REQUEST deadline (serve/resilience/deadline.py) when
    one is installed, so a web/API deadline propagates through planner
    stages without threading a parameter through every call — whichever
    of the two budgets lapses first wins."""

    def __init__(self, timeout_ms: Optional[float]):
        self.t0 = time.perf_counter()
        self.timeout_ms = timeout_ms
        # lazy import: guards loads before/without the serve package
        from geomesa_tpu.serve.resilience import deadline as _rdl
        self._request = _rdl.current()

    def check(self, stage: str) -> None:
        if self._request is not None:
            self._request.check(stage)  # raises DeadlineExceeded
        if self.timeout_ms is None:
            return
        elapsed = (time.perf_counter() - self.t0) * 1000
        if elapsed > self.timeout_ms:
            raise QueryTimeout(
                f"Query exceeded {self.timeout_ms}ms at stage {stage!r} "
                f"({elapsed:.0f}ms elapsed)")
