"""Device-side index-key encoding: fp62 planes, curve cells, Morton planes.

The reference encodes index keys row-by-row on the ingest host
(Z3IndexKeySpace.toIndexKey, /root/reference/geomesa-index-api/src/main/scala/
org/locationtech/geomesa/index/index/z3/Z3IndexKeySpace.scala:64-96). On a
single-core host that pass costs minutes at 100M rows, so here the whole
encode runs on the accelerator:

  host                         device (one jitted kernel)
  ----                         --------------------------
  u = x - dom_lo  (1 pass)  →  IEEE-decode u bits → fp62 hi/lo int32 planes
  f32 casts       (1 pass)  →  21-bit curve cells (f32 mul+floor)
                            →  Morton spread → 3×21-bit sort planes
                            →  lax.sort → permutation → fused gather

fp62 semantics (shared host/device contract — device.fp62 implements the
same formula in f64): ``v = clamp(floor(u * 2^shift), 0, span*2^shift)`` where
``shift = 62 - ceil(log2(domain_span))``. Because the scale is a power of two,
the device can compute v EXACTLY from the raw IEEE-754 bits of u (mantissa
funnel-shift by exponent) — no f64 arithmetic needed on TPU. The quantum
(2^-53 deg for lon) is finer than the f64 ulp of any in-domain coordinate, so
lexicographic (hi, lo) int32 comparison reproduces the host's f64 predicate
exactly.

Curve cells intentionally use f32 math (`cells_f32`), identically on host and
device: the ±1-cell difference vs the exact f64 SFC normalize is absorbed by
padding query covers by one cell per dimension (`curves/ranges` callers).
Cells only place rows in the sorted layout; exactness comes from fp62 masks.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

# fp62 shift per domain: lon span 360 ⊂ [0, 512) → shift 53; lat span 180 ⊂
# [0, 256) → shift 54. Both yield v < 2^62 (31+31 bit planes).
LON_SHIFT = 53
LAT_SHIFT = 54

_M31 = (1 << 31) - 1
_M21 = (1 << 21) - 1


# -- shared f32 cell quantization (host numpy == device jnp, op for op) -----


def cells_f32(xp, v_f32, lo: float, inv_cell: float, max_index: int):
    """Curve cell of each coordinate: floor((v - lo) * inv_cell) clamped.

    ``xp`` is the array namespace (numpy or jax.numpy); all math is f32 so the
    host build path and the device build path place every row in the same
    cell (IEEE f32 ops round identically)."""
    f = (v_f32 - xp.float32(lo)) * xp.float32(inv_cell)
    c = xp.floor(f).astype(xp.int32)
    return xp.clip(c, 0, max_index)


def lon_cells(xp, x_f32, bits: int = 21):
    return cells_f32(xp, x_f32, -180.0, (1 << bits) / 360.0, (1 << bits) - 1)


def lat_cells(xp, y_f32, bits: int = 21):
    return cells_f32(xp, y_f32, -90.0, (1 << bits) / 180.0, (1 << bits) - 1)


def time_cells(xp, off_f32, max_offset: int, bits: int = 21):
    """Offsets are int period-units < 2^24 → exact in f32."""
    return cells_f32(xp, off_f32, 0.0, (1 << bits) / float(max_offset),
                     (1 << bits) - 1)


# The f32 cell can differ from the exact f64 SFC normalize by at most
# ceil(2^bits * 2^-23) cells (f32 relative error through one subtract and one
# multiply) — covers pad their normalized query boxes by this much.
def cell_pad(bits: int) -> int:
    return max(1, 1 << max(0, bits - 22))


# -- host fp62 (f64 reference formula; device bit-math must match exactly) --


def fp62_host(u: np.ndarray, shift: int, span: float) -> Tuple[np.ndarray, np.ndarray]:
    """``u`` = coordinate minus domain min, already f64-rounded. Returns
    (hi, lo) int32 planes of v = clamp(floor(u * 2^shift), 0, span*2^shift)."""
    v = np.floor(np.ldexp(np.asarray(u, dtype=np.float64), shift)).astype(np.int64)
    np.clip(v, 0, int(span * (1 << shift)), out=v)
    return (v >> 31).astype(np.int32), (v & _M31).astype(np.int32)


# -- device fp62 from IEEE-754 bits -----------------------------------------


def f64_bits_u32(u: np.ndarray) -> np.ndarray:
    """Host view of an f64 array as little-endian uint32 pairs, shape (n, 2)
    — a zero-copy reinterpret, uploaded as one contiguous buffer."""
    u = np.ascontiguousarray(u, dtype=np.float64)
    return u.view(np.uint32).reshape(-1, 2)


def fp62_from_bits(jnp, bits_lo, bits_hi, shift: int, span: float):
    """Device: (hi, lo) int32 fp62 planes from the raw IEEE-754 bits of u.

    v = clamp(floor(u * 2^shift), 0, span << shift) computed exactly with
    uint32 ops: u = m * 2^(e-1075) (m = 53-bit mantissa incl. implicit bit,
    e = biased exponent), so floor(u * 2^shift) is m funnel-shifted by
    s = e - 1075 + shift. Negative u (sign bit) clamps to 0; u > span clamps
    to the top plane pair. Works for every finite input the host formula
    accepts (subnormals have e=0 → shift ≤ -1022+shift ≪ 0 → v=0)."""
    bl = bits_lo.astype(jnp.uint32)
    bh = bits_hi.astype(jnp.uint32)
    sign = (bh >> 31) != 0
    e = ((bh >> 20) & 0x7FF).astype(jnp.int32)
    m_hi = ((bh & 0xFFFFF) | jnp.where(e > 0, jnp.uint32(1 << 20), jnp.uint32(0)))
    # mantissa = m_hi (21 bits, z-bits 32..52) : bl (32 bits, z-bits 0..31)
    s = e - 1075 + shift  # net left-shift of the 53-bit mantissa

    # left shift by s ∈ [0, 9] (u >= 0.5 after scale): v spans ≤ 62 bits
    sl = jnp.clip(s, 0, 31).astype(jnp.uint32)
    lo_l = bl << sl                                  # low 32 of (bl << s)
    carry = jnp.where(sl > 0, bl >> (32 - sl), jnp.uint32(0))
    hi_l = (m_hi << sl) | carry                      # bits 32..62 of v
    # right shift by -s ∈ [1, 53+] (u < 0.5 after scale)
    sr = jnp.clip(-s, 0, 31).astype(jnp.uint32)
    lo_r = jnp.where(
        sr < 32,
        (bl >> sr) | jnp.where(sr > 0, m_hi << (32 - sr), jnp.uint32(0)),
        m_hi >> jnp.clip(sr - 32, 0, 31))
    lo_r = jnp.where(-s > 52, jnp.uint32(0), lo_r)
    hi_r = jnp.where(sr < 32, m_hi >> sr, jnp.uint32(0))

    v_lo32 = jnp.where(s >= 0, lo_l, lo_r)           # v bits 0..31
    v_hi = jnp.where(s >= 0, hi_l, hi_r)             # v bits 32..62
    # repack 64-bit (v_hi:v_lo32) into 31-bit planes: hi31 = v >> 31
    hi31 = ((v_hi << 1) | (v_lo32 >> 31)) & jnp.uint32(_M31)
    lo31 = v_lo32 & jnp.uint32(_M31)
    # clamps: negative → 0; overflow (v > span<<shift) → top
    top = int(span * (1 << shift))
    top_hi, top_lo = top >> 31, top & _M31
    over = (hi31 > top_hi) | ((hi31 == top_hi) & (lo31 > top_lo))
    zero = sign | (e == 0)
    hi31 = jnp.where(zero, jnp.uint32(0), jnp.where(over, jnp.uint32(top_hi), hi31))
    lo31 = jnp.where(zero, jnp.uint32(0), jnp.where(over, jnp.uint32(top_lo), lo31))
    return hi31.astype(jnp.int32), lo31.astype(jnp.int32)


# -- Morton plane spread (device) -------------------------------------------


def spread3_7(jnp, v):
    """Spread a 7-bit uint32 so bit i lands at bit 3i (standard magic masks,
    32-bit variant of curves/zorder spread3)."""
    v = v.astype(jnp.uint32) & jnp.uint32(0x7F)
    v = (v | (v << 8)) & jnp.uint32(0x0700F)
    v = (v | (v << 4)) & jnp.uint32(0x430C3)
    v = (v | (v << 2)) & jnp.uint32(0x49249)
    return v


def z3_planes(jnp, xi21, yi21, ti21):
    """(p0, p1, p2) int32 21-bit planes of z3_encode(xi, yi, ti), major→minor
    — p0 = z >> 42, matching spatial._split63 of the host curves/zorder path
    (z bit 3i+0 = x bit i, +1 = y, +2 = t)."""
    out = []
    for sh in (14, 7, 0):
        px = spread3_7(jnp, (xi21 >> sh))
        py = spread3_7(jnp, (yi21 >> sh))
        pt = spread3_7(jnp, (ti21 >> sh))
        out.append((px | (py << 1) | (pt << 2)).astype(jnp.int32))
    return tuple(out)


def spread2_16(jnp, v):
    """Spread a 16-bit uint32 so bit i lands at bit 2i."""
    v = v.astype(jnp.uint32) & jnp.uint32(0xFFFF)
    v = (v | (v << 8)) & jnp.uint32(0x00FF00FF)
    v = (v | (v << 4)) & jnp.uint32(0x0F0F0F0F)
    v = (v | (v << 2)) & jnp.uint32(0x33333333)
    v = (v | (v << 1)) & jnp.uint32(0x55555555)
    return v


def z2_planes(jnp, xi, yi, bits: int = 21):
    """(p0, p1, p2) int32 21-bit planes of z2_encode(xi, yi) (≤ 21-bit dims,
    42-bit z; p0 = z >> 42 = 0 for 21-bit inputs — kept for a uniform
    3-plane sort signature)."""
    ex_lo = spread2_16(jnp, xi)
    ex_hi = spread2_16(jnp, xi >> 16)
    ey_lo = spread2_16(jnp, yi)
    ey_hi = spread2_16(jnp, yi >> 16)
    lo = ex_lo | (ey_lo << 1)        # z bits 0..31
    hi = ex_hi | (ey_hi << 1)        # z bits 32..61 (stored at 0..29)
    p2 = (lo & jnp.uint32(_M21)).astype(jnp.int32)
    p1 = (((lo >> 21) | (hi << 11)) & jnp.uint32(_M21)).astype(jnp.int32)
    p0 = ((hi >> 10) & jnp.uint32(_M21)).astype(jnp.int32)
    return p0, p1, p2
