"""Shared index/plan/result datatypes (≙ reference index.api package:
QueryStrategy/FilterStrategy/QueryPlan, api/package.scala:221-291)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from geomesa_tpu.features.table import FeatureTable
from geomesa_tpu.filter import ir


@dataclass
class IndexScanPlan:
    """One executable strategy: device primary params + residual split.

    ≙ QueryStrategy (api/GeoMesaFeatureIndex.getQueryStrategy:248): the index
    chosen, its primary key-space constraints (here: padded int box / time
    window arrays), and the filter remainder split between device and host.
    """

    index: object                                  # BaseIndex
    primary_kind: str                              # "point_boxes"|"bbox_overlap"|"none"
    boxes_loose: Optional[np.ndarray] = None       # (B,8) int32 fp62 planes
    windows: Optional[np.ndarray] = None           # (T,4) int32 exact bin/off
    residual_device: Optional[tuple] = None        # (key, params, fn)
    residual_host: Optional[ir.Filter] = None      # host-refined remainder
    full_filter: Optional[ir.Filter] = None        # original, for fallbacks
    cost: float = 0.0
    empty: bool = False                            # provably no results
    explain: Dict[str, object] = field(default_factory=dict)


@dataclass
class QueryResult:
    """Materialized query output (≙ the reader side of QueryPlanner.runQuery)."""

    indices: np.ndarray          # row indices into the master FeatureTable
    table: FeatureTable          # hydrated rows (post filter/transform)
    plan: Optional[IndexScanPlan] = None

    @property
    def count(self) -> int:
        return len(self.indices)
