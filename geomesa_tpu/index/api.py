"""Shared index/plan/result datatypes (≙ reference index.api package:
QueryStrategy/FilterStrategy/QueryPlan, api/package.scala:221-291)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from geomesa_tpu.features.table import FeatureTable
from geomesa_tpu.filter import ir


@dataclass
class IndexScanPlan:
    """One executable strategy: device primary params + residual split.

    ≙ QueryStrategy (api/GeoMesaFeatureIndex.getQueryStrategy:248): the index
    chosen, its primary key-space constraints (here: padded int box / time
    window arrays), and the filter remainder split between device and host.
    """

    index: object                                  # BaseIndex
    primary_kind: str                              # "point_boxes"|"bbox_overlap"|"none"
    boxes_loose: Optional[np.ndarray] = None       # (B,8) int32 fp62 planes
    windows: Optional[np.ndarray] = None           # (T,4) int32 exact bin/off
    residual_device: Optional[tuple] = None        # (key, params, fn)
    residual_host: Optional[ir.Filter] = None      # host-refined remainder
    full_filter: Optional[ir.Filter] = None        # original, for fallbacks
    cost: float = 0.0
    empty: bool = False                            # provably no results
    explain: Dict[str, object] = field(default_factory=dict)
    # attribute-index pruning: [lo, hi) slices (into the index's sorted
    # order) of candidate rows; when set, the device scan gathers + masks
    # only these rows (≙ a contiguous key-range scan instead of a full-table
    # scan). Positions materialize lazily — pricing needs only the count.
    candidate_slices: Optional[List[Tuple[int, int]]] = None
    # range-pruning cache (planner._pruned_blocks): False = not yet computed,
    # None = pruning declined (full scan), ndarray = candidate block ids
    blocks: object = False

    @property
    def device_exact(self) -> bool:
        """True when the plan resolves entirely on device: a primary/residual
        mask scan with no host refinement, candidate pruning, or fid lookup.
        The single home of this predicate — prepared queries, density,
        scan_mask, and KNN pipelining all branch on it."""
        return (not self.empty and self.primary_kind != "fid"
                and self.residual_host is None
                and self.candidate_slices is None and self.index is not None)

    @property
    def n_candidates(self) -> Optional[int]:
        if self.candidate_slices is None:
            return None
        return sum(h - l for l, h in self.candidate_slices)

    def candidate_positions(self) -> np.ndarray:
        return np.concatenate(
            [np.arange(l, h, dtype=np.int64) for l, h in self.candidate_slices]
        ) if self.candidate_slices else np.empty(0, dtype=np.int64)


@dataclass
class UnionScanPlan:
    """OR → multiple strategies: each OR branch plans independently and the
    executor unions the row sets (≙ FilterSplitter's OR expansion,
    planning/FilterSplitter.scala:61-103, where an Or becomes a FilterPlan
    with several FilterStrategies). When every branch resolves to a
    device-exact mask on the SAME index, the union is a single fused
    OR-of-masks scan; otherwise row sets union on the host."""

    branches: List[tuple]            # [(child_filter, IndexScanPlan), ...]
    full_filter: Optional[ir.Filter] = None
    cost: float = 0.0
    empty: bool = False
    explain: Dict[str, object] = field(default_factory=dict)

    # duck-typed surface shared with IndexScanPlan consumers
    primary_kind: str = "union"
    candidate_slices = None
    residual_host = None
    index = None
    blocks: object = None
    boxes_loose = None
    windows = None

    @property
    def device_exact(self) -> bool:
        return False  # prepared/count fast paths run per-branch instead

    def same_index_device_exact(self):
        """The shared index when every branch is a device-exact mask scan on
        one index, else None (enables the fused OR-of-masks path)."""
        idxs = {id(p.index) for _, p in self.branches}
        if len(idxs) != 1:
            return None
        for _, p in self.branches:
            if not p.device_exact:
                return None
        return self.branches[0][1].index


@dataclass
class QueryResult:
    """Materialized query output (≙ the reader side of QueryPlanner.runQuery)."""

    indices: np.ndarray          # row indices into the master FeatureTable
    table: FeatureTable          # hydrated rows (post filter/transform)
    plan: Optional[IndexScanPlan] = None

    @property
    def count(self) -> int:
        return len(self.indices)
