"""Attribute index: value-sorted rows + tiered spatio-temporal order.

≙ reference AttributeIndex (index/attribute/AttributeIndexKeySpace.scala:35,
AttributeIndexKey.scala:23-79): rows keyed ``[attr value][tier]`` where the
tier is the Z3/date secondary key. The KV-store's lexicoded-bytes trick is
unnecessary here — the TPU build sorts typed columns directly (string columns
sort by dictionary code; vocabularies are built sorted so code order IS
lexicographic order).

Query path: equality / range / IN predicates on the attribute become
``searchsorted`` slices over the host copy of the sorted values (≙ the row
ranges of GeoMesaFeatureIndex.getQueryStrategy), producing candidate
positions; the device scan gathers ONLY those rows and applies the remaining
boxes/windows/residual mask (≙ scanning one key range with the pushdown
filter attached, instead of the full table).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from geomesa_tpu.curves.binnedtime import time_to_binned_time
from geomesa_tpu.features.table import StringColumn
from geomesa_tpu.filter import ir
from geomesa_tpu.index.api import IndexScanPlan
from geomesa_tpu.index.spatial import BaseSpatialIndex

# predicates an attribute slice can consume entirely
_RANGE_OPS = {"=", "<", "<=", ">", ">="}


def indexed_attributes(sft) -> List[str]:
    """Attributes flagged for indexing: ``index=true``/``index=full`` options
    (≙ the reference's attribute-spec opt, SimpleFeatureTypes) plus ``attr:X``
    entries in ``geomesa.indices``."""
    out = []
    for a in sft.attributes:
        if a.is_geometry:
            continue
        if a.options.get("index", "").lower() in ("true", "full", "join"):
            out.append(a.name)
    raw = sft.user_data.get("geomesa.indices", "")
    for part in raw.split(","):
        if ":" in part:
            name, _, attr = part.partition(":")
            if name == "attr" and attr and attr not in out:
                out.append(attr)
    return out


class AttributeIndex(BaseSpatialIndex):
    """One instance per indexed attribute (like the reference: one
    GeoMesaFeatureIndex per attribute + secondary tier)."""

    name = "attr"
    temporal = True   # tier carries (bin, off) when the sft has a dtg
    points = True

    def __init__(self, sft, table, attr: str):
        self.attr = attr
        spec = sft.attribute(attr)
        self.type_name = spec.type_name
        g = sft.geometry_attribute
        super().__init__(sft, table)
        self.points = g is not None and g.type_name == "Point"

    @classmethod
    def supports(cls, sft) -> bool:
        return bool(indexed_attributes(sft))

    def _sort_keys(self):
        col = self.table.columns[self.attr]
        if isinstance(col, StringColumn):
            vals = col.codes.astype(np.int64)
            self._vocab = col.vocab
        else:
            vals = np.asarray(col)
            self._vocab = None
        self._vals = vals
        # secondary tier: (bin, off) via dtg when present, else raw order.
        # Keys are major-first; value dtypes may be float, which keeps this
        # index on the host lexsort path (the device sort needs int32 planes).
        if self.dtg is not None:
            ms = np.asarray(self.table.columns[self.dtg], dtype=np.int64)
            bins, offs = time_to_binned_time(ms, self.period)
            return [vals, bins, offs]
        return [vals]

    @property
    def _sorted_vals(self) -> np.ndarray:
        if getattr(self, "_sorted_vals_cache", None) is None:
            self._sorted_vals_cache = self._vals[self.perm]
        return self._sorted_vals_cache

    # -- predicate extraction ------------------------------------------------

    def _split_attr_predicate(self, f: ir.Filter):
        """(consumable predicates on self.attr, remaining filter). Only
        AND-rooted (or single) filters qualify — OR across attributes falls
        back to other strategies (≙ FilterSplitter per-index primaries)."""
        children = f.children if isinstance(f, ir.And) else (f,)
        if isinstance(f, ir.Or):
            return [], f
        mine, rest = [], []
        for c in children:
            if isinstance(c, ir.Cmp) and c.attr == self.attr and c.op in _RANGE_OPS:
                mine.append(c)
            elif isinstance(c, ir.In) and c.attr == self.attr:
                mine.append(c)
            else:
                rest.append(c)
        return mine, (ir.and_filters(rest) if rest else None)

    def _value_key(self, v):
        """User value → sort-domain value."""
        if self._vocab is not None:
            return np.searchsorted(np.asarray(self._vocab, dtype=object), v), v
        return v, v

    def _slices(self, preds) -> Optional[List[Tuple[int, int]]]:
        """Candidate [lo, hi) position slices from the predicates (None =
        cannot consume: unsupported value type)."""
        sv = self._sorted_vals
        n = len(sv)
        lo, hi = 0, n
        points: Optional[List[Tuple[int, int]]] = None
        for p in preds:
            if isinstance(p, ir.In):
                pts = []
                for v in p.values:
                    l, h = self._eq_slice(v)
                    pts.append((l, h))
                points = pts if points is None else [
                    (max(l0, l1), min(h0, h1))
                    for (l0, h0) in points for (l1, h1) in pts]
                continue
            code, raw = self._value_key(p.value)
            if self._vocab is not None and p.op in ("<", "<=", ">", ">=", "="):
                # string ordering: codes are lexicographic. Map the bound to a
                # CODE CUTPOINT first (codes < cut satisfy </<=; codes >= cut
                # satisfy >/>=) — bounds absent from the vocabulary land
                # between codes, so the cut, not the insertion code, is exact.
                if p.op == "=":
                    l, h = self._eq_slice(raw)
                    lo, hi = max(lo, l), min(hi, h)
                    continue
                vocab = np.asarray(self._vocab, dtype=object)
                vside = "left" if p.op in ("<", ">=") else "right"
                cut = int(np.searchsorted(vocab, raw, side=vside))
                pos = int(np.searchsorted(sv, cut, side="left"))
                if p.op in ("<", "<="):
                    hi = min(hi, pos)
                else:
                    lo = max(lo, pos)
                continue
            if p.op == "=":
                l = int(np.searchsorted(sv, code, side="left"))
                h = int(np.searchsorted(sv, code, side="right"))
                lo, hi = max(lo, l), min(hi, h)
            elif p.op in ("<", "<="):
                hi = min(hi, int(np.searchsorted(sv, code,
                                                 side="left" if p.op == "<" else "right")))
            else:  # > >=
                lo = max(lo, int(np.searchsorted(sv, code,
                                                 side="right" if p.op == ">" else "left")))
        if points is not None:
            return [(max(l, lo), min(h, hi)) for l, h in points if min(h, hi) > max(l, lo)]
        return [(lo, hi)] if hi > lo else []

    def _eq_slice(self, v) -> Tuple[int, int]:
        if self._vocab is not None:
            vocab = np.asarray(self._vocab, dtype=object)
            pos = int(np.searchsorted(vocab, v))
            if pos >= len(vocab) or vocab[pos] != v:
                return (0, 0)
            code = pos
        else:
            code = v
        return (int(np.searchsorted(self._sorted_vals, code, side="left")),
                int(np.searchsorted(self._sorted_vals, code, side="right")))

    # -- planning ------------------------------------------------------------

    def plan(self, f: ir.Filter) -> Optional[IndexScanPlan]:
        mine, rest = self._split_attr_predicate(f)
        if not mine:
            return None
        try:
            slices = self._slices(mine)
        except TypeError:
            return None  # incomparable value type
        if slices is not None and not slices:
            return IndexScanPlan(self, "none", empty=True, full_filter=f, cost=0.0,
                                 explain={"index": f"attr:{self.attr}"})
        if slices is None:
            return None
        # remaining filter plans through the base machinery (boxes/windows/
        # residual split); the slice enforces the attr predicates exactly
        base = super().plan(rest if rest is not None else ir.Include())
        base.candidate_slices = slices
        base.full_filter = f
        base.cost = 0.5 if not base.empty else 0.0  # exact-slice strategies win ties
        base.explain.update({
            "index": f"attr:{self.attr}",
            "predicates": [type(p).__name__ for p in mine],
            "candidates": base.n_candidates,
        })
        return base
