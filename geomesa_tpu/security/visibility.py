"""Visibility expressions + authorizations.

≙ reference `geomesa-security` (SURVEY.md §2.11): `VisibilityEvaluator`
(security/VisibilityEvaluator.scala:22,156 — Accumulo-style boolean label
expressions ``admin&(user|ops)``), `AuthorizationsProvider` SPI, and the
per-feature `VisibilityFilter`. Columnar twist: visibilities are dictionary
-encoded per feature table, so a query evaluates each DISTINCT expression
against the caller's auths once on the host, and enforcement on device is a
tiny code-membership mask — no per-row expression evaluation anywhere.

Grammar (Accumulo visibility subset)::

    expr   := term (('&' | '|') term)*    # one operator kind per level
    term   := label | quoted | '(' expr ')'
    label  := [A-Za-z0-9_.:-]+            # or "quoted string"

Empty expression = visible to everyone.
"""

from __future__ import annotations

import re
from typing import Iterable, List, Optional, Sequence

import numpy as np

_LABEL = re.compile(r'[A-Za-z0-9_.:+/-]+|"(?:[^"\\]|\\.)*"')


class VisibilityError(ValueError):
    pass


def parse_visibility(expr: str):
    """Expression AST: label str | ('&'|'|', [children]). Raises
    VisibilityError on malformed input."""
    expr = expr.strip()
    if not expr:
        return None
    node, pos = _parse_expr(expr, 0)
    if pos != len(expr):
        raise VisibilityError(f"Trailing input in visibility {expr!r}")
    return node


def _parse_expr(s: str, pos: int):
    terms = []
    op = None
    while True:
        term, pos = _parse_term(s, pos)
        terms.append(term)
        if pos >= len(s) or s[pos] == ")":
            break
        c = s[pos]
        if c not in "&|":
            raise VisibilityError(f"Expected & or | at {s[pos:]!r}")
        if op is None:
            op = c
        elif op != c:
            raise VisibilityError(
                f"Mixed & and | need parentheses in {s!r} (Accumulo rule)")
        pos += 1
    if len(terms) == 1:
        return terms[0], pos
    return (op, terms), pos


def _parse_term(s: str, pos: int):
    if pos >= len(s):
        raise VisibilityError(f"Unexpected end of visibility {s!r}")
    if s[pos] == "(":
        node, pos = _parse_expr(s, pos + 1)
        if pos >= len(s) or s[pos] != ")":
            raise VisibilityError(f"Unclosed paren in {s!r}")
        return node, pos + 1
    m = _LABEL.match(s, pos)
    if not m:
        raise VisibilityError(f"Bad label at {s[pos:]!r}")
    label = m.group(0)
    if label.startswith('"'):
        label = label[1:-1].replace('\\"', '"').replace("\\\\", "\\")
    return label, m.end()


def evaluate(expr, auths: Iterable[str]) -> bool:
    """AST (or raw string) against an auth set."""
    if isinstance(expr, str):
        expr = parse_visibility(expr)
    if expr is None:
        return True
    auth_set = set(auths)

    def walk(node) -> bool:
        if isinstance(node, str):
            return node in auth_set
        op, children = node
        return (all if op == "&" else any)(walk(c) for c in children)

    return walk(expr)


def allowed_codes(vocab: Sequence[str], auths: Iterable[str]) -> np.ndarray:
    """Dictionary codes of visibility expressions the auths may see — the
    once-per-distinct-expression evaluation that replaces per-row checks."""
    auth_set = set(auths)
    return np.asarray(
        [i for i, expr in enumerate(vocab) if evaluate(expr, auth_set)],
        dtype=np.int32)


class AuthorizationsProvider:
    """Pluggable auth lookup (≙ AuthorizationsProvider SPI; the default
    returns a fixed set, mirroring DefaultAuthorizationsProvider)."""

    def __init__(self, auths: Optional[Sequence[str]] = None):
        self._auths = list(auths or [])

    def get_authorizations(self) -> List[str]:
        return list(self._auths)
