"""Security: visibility labels + authorizations (≙ geomesa-security)."""

from geomesa_tpu.security.visibility import (AuthorizationsProvider,
                                             VisibilityError, allowed_codes,
                                             evaluate, parse_visibility)

__all__ = ["AuthorizationsProvider", "VisibilityError", "allowed_codes",
           "evaluate", "parse_visibility"]
