"""Query tracing: nested spans, per-query traces, a bounded ring of recents.

≙ the reference's Explainer threaded through QueryPlanner (every scan
accounts for its plan, ranges, and timings) plus the QueryEvent audit trail
(index/audit/QueryEvent.scala) — upgraded to a span tree so time attributes
to *stages*, not just plan-vs-scan. The load-bearing distinction is
``device_scan`` (dispatch: host work to enqueue the XLA computation) vs
``device_wait`` (time inside ``block_until_ready``): on a tunneled chip the
dispatch floor and the device compute are different bottlenecks, and BENCH
showed blocking p50 is dispatch/RTT-bound — this layer makes that split
visible per-query.

Span kinds (the fixed vocabulary hot paths use):

  plan             filter parse + strategy selection
  range_decompose  key-range → candidate-block cover computation
  queue_wait       time spent queued in the micro-batching scheduler before
                   its batch dispatched (serve/scheduler.py)
  scan             umbrella execution stage (staging + kernel + readback);
                   its SELF time is constant staging / host glue
  device_scan      kernel dispatch (host-side enqueue, async)
  device_wait      block_until_ready on the dispatched result
  refine           host f64 re-evaluation of device candidates
  aggregate        host-side merge/summarize (density decode, join merge…)
  serialize        row hydration / output encoding
  wal_append       write-ahead-log frame write (durability/wal.py)
  wal_fsync        group-commit fsync (the durability tax, measured)
  recovery         snapshot load + WAL replay at DataStore.open()

Usage::

    with trace("query", type="gdelt", filter=str(f)) as t:
        with span("plan"):
            ...
    RING.recent()          # most-recent-first trace dicts (the audit ring)
    with disabled():       # hot-loop opt-out: spans become no-ops
        ...

Every span (and root trace) also feeds ``metrics.REGISTRY`` as a histogram
timer under its name, so the Prometheus surface gets per-stage percentiles
for free — spans REPLACE the ad-hoc ``REGISTRY.time(...)`` calls on the hot
paths. ``trace()`` nests: opened under an active trace it degrades to a
plain span, so datastore-level and planner-level roots compose.

Thread model: the current trace is thread-local (one query per thread, the
ThreadingHTTPServer model); the ring buffer is process-global and locked.

Fleet context (obs/federation.py rides on these primitives): every root
trace carries a process-stable ``node_id``/``role`` dimension and a
globally-unique ``global_id`` (``<node>-<local id>``). A proxied request
propagates its context over HTTP (X-Trace-Id / X-Span-Id / X-Trace-Node /
X-Trace-Sampled — ``inject_headers``/``extract_headers``); the receiving
process opens its root trace as a CHILD of the remote parent
(``remote_parent``), sharing the parent's global id so a stitcher can
reassemble ONE cross-process tree from the per-node halves.
"""

from __future__ import annotations

import itertools
import os
import threading
import time
import zlib
from collections import deque
from typing import Dict, Iterator, List, Optional

from geomesa_tpu.metrics import REGISTRY as _REGISTRY

SPAN_KINDS = ("plan", "range_decompose", "queue_wait", "scan", "device_scan",
              "device_wait", "refine", "aggregate", "serialize",
              "wal_append", "wal_fsync", "recovery",
              # query-lifecycle resilience (serve/resilience/): a request
              # cancelled at its deadline BEFORE device dispatch, a count
              # degraded to the stats estimator, a request shed by admission
              # control — the overload test asserts on these leaves
              "cancel", "degrade", "shed",
              # long-running build phase (encode/upload/sort — obs/profiling
              # PROGRESS): a traced ingest that triggers a rebuild
              # attributes the build stages instead of one opaque span
              "build_phase",
              # cross-process collective op (cluster/: psum dispatch,
              # host allgather, barrier, row exchange) — stitched traces
              # show where a distributed query's wall time went
              "collective")

_pc = time.perf_counter  # cached: spans sit on µs-scale hot paths

class _Local(threading.local):
    # class-level defaults make `_local.trace` a plain read on threads that
    # never traced (no getattr-with-default on the hot path)
    trace = None
    stack = None
    remote = None  # pending RemoteParent consumed by the next root trace


_local = _Local()
_ids = itertools.count(1)
_span_ids = itertools.count(1)


# -- node identity (the fleet dimension on every trace/event/metric) ----------


class _Node:
    id: Optional[str] = None
    role = "standalone"


def node_id() -> str:
    """Process-stable node identity: GEOMESA_TPU_NODE_ID, else
    ``<short-hostname>-<pid>`` (unique per incarnation on one host — the
    shape localhost fleets and tests produce)."""
    nid = _Node.id
    if nid is None:
        from geomesa_tpu import config
        nid = str(config.NODE_ID.get() or "").strip()
        if not nid:
            try:
                import socket as _socket
                host = _socket.gethostname().split(".")[0]
            except OSError:
                host = "node"
            nid = f"{host}-{os.getpid()}"
        _Node.id = nid
    return nid


def node_role() -> str:
    return _Node.role


def set_node_role(role: str) -> None:
    """Stamp this process's fleet role (primary / replica / router /
    standalone) — replication and router constructors call it so every
    trace/flight event carries the role it was produced under."""
    _Node.role = str(role)


def _reset_node_for_tests() -> None:
    _Node.id = None
    _Node.role = "standalone"


# -- cross-process propagation ------------------------------------------------


class RemoteParent:
    """The extracted upstream context: the remote parent this process's
    next root trace is a child of."""

    __slots__ = ("trace_id", "span_id", "node", "sampled")

    def __init__(self, trace_id: str, span_id: Optional[int],
                 node: Optional[str], sampled: bool):
        self.trace_id = str(trace_id)
        self.span_id = int(span_id) if span_id else None
        self.node = node
        self.sampled = bool(sampled)

    def to_dict(self) -> dict:
        out = {"trace": self.trace_id}
        if self.span_id is not None:
            out["span"] = self.span_id
        if self.node is not None:
            out["node"] = self.node
        return out


def extract_headers(headers) -> Optional[RemoteParent]:
    """RemoteParent from incoming HTTP headers (None when the request
    carries no trace context or propagation is off)."""
    if headers is None:
        return None
    tid = headers.get("X-Trace-Id")
    if not tid:
        return None
    from geomesa_tpu import config
    if not config.FED_PROPAGATE.get():
        return None
    try:
        span_id = int(headers.get("X-Span-Id") or 0)
    except (TypeError, ValueError):
        span_id = 0
    return RemoteParent(tid, span_id or None, headers.get("X-Trace-Node"),
                        str(headers.get("X-Trace-Sampled") or "0") == "1")


def inject_headers() -> Dict[str, str]:
    """Propagation headers for an outbound hop made under the current
    trace: the trace's global id, the CURRENT span's id (assigned on
    demand — the remote half parents under it), this node, and the
    sampling decision (sticky once made: deterministic on the global id,
    so every hop of one request agrees without coordination)."""
    tr = _local.trace
    if tr is None:
        return {}
    from geomesa_tpu import config
    if not config.FED_PROPAGATE.get():
        return {}
    sp = _local.stack[-1]
    if sp.span_id is None:
        sp.span_id = next(_span_ids)
    gid = tr.global_id
    if not tr.sampled_hint:
        rate = float(config.OBS_SAMPLE.get())
        if rate > 0 and (zlib.crc32(gid.encode()) % 10_000) < rate * 10_000:
            tr.sampled_hint = True
    return {"X-Trace-Id": gid,
            "X-Span-Id": str(sp.span_id),
            "X-Trace-Node": node_id(),
            "X-Trace-Sampled": "1" if tr.sampled_hint else "0"}


class remote_parent:
    """Context manager binding an extracted RemoteParent to this thread:
    the next ROOT trace opened inside becomes its child (adopts the
    remote global id, records the parent span, honors the propagated
    sampling decision). None is a no-op, so callers pass
    ``extract_headers(...)`` unconditionally."""

    __slots__ = ("_ctx", "_prev")

    def __init__(self, ctx: Optional[RemoteParent]):
        self._ctx = ctx

    def __enter__(self):
        self._prev = _local.remote
        if self._ctx is not None:
            _local.remote = self._ctx
        return self._ctx

    def __exit__(self, *exc):
        _local.remote = self._prev
        return False


class _State:
    enabled = True


_state = _State()


def set_enabled(on: bool) -> None:
    """Globally enable/disable tracing (spans become no-ops when off)."""
    _state.enabled = bool(on)


class disabled:
    """Context manager: suspend tracing AND span→registry feeding inside.
    The perf-budget guard compares against this mode."""

    def __enter__(self):
        self._prev = _state.enabled
        _state.enabled = False
        return self

    def __exit__(self, *exc):
        _state.enabled = self._prev
        return False


class Span:
    """One timed stage. ``self_ms`` is duration minus child durations —
    the time this stage spent NOT delegated to a sub-stage. ``children`` is
    None until the first child attaches (most spans are leaves; the lazy
    list keeps leaf allocation to one object on the hot path)."""

    __slots__ = ("name", "kind", "attrs", "duration_ms", "children",
                 "span_id")

    def __init__(self, name: str, kind: Optional[str], attrs: Optional[dict]):
        self.name = name
        self.kind = kind if kind is not None else (
            name if name in SPAN_KINDS else "span")
        self.attrs = attrs
        self.duration_ms = 0.0
        self.children: Optional[List[Span]] = None
        # assigned on demand (inject_headers) when this span parents a
        # remote child — the stitcher's attachment point
        self.span_id: Optional[int] = None

    def add_child(self, node: "Span") -> None:
        c = self.children
        if c is None:
            self.children = [node]
        else:
            c.append(node)

    @property
    def self_ms(self) -> float:
        if not self.children:
            return self.duration_ms
        return self.duration_ms - sum(c.duration_ms for c in self.children)

    def walk(self) -> Iterator["Span"]:
        yield self
        if self.children:
            for c in self.children:
                yield from c.walk()

    def to_dict(self) -> dict:
        d = {"name": self.name, "kind": self.kind,
             "duration_ms": round(self.duration_ms, 3),
             "self_ms": round(self.self_ms, 3)}
        if self.span_id is not None:
            d["span_id"] = self.span_id
        if self.attrs:
            d["attrs"] = {k: str(v) for k, v in self.attrs.items()}
        if self.children:
            d["children"] = [c.to_dict() for c in self.children]
        return d


class QueryTrace:
    """One query's span tree (≙ one QueryEvent, with stage attribution).
    ``error`` is the exception type name when the traced block raised —
    the tail sampler's keep-always signal."""

    __slots__ = ("trace_id", "name", "ts_ms", "root", "error",
                 "parent", "sampled_hint", "_global_id")

    def __init__(self, name: str, attrs: Optional[dict]):
        self.trace_id = next(_ids)
        self.name = name
        self.ts_ms = int(time.time() * 1000)
        self.root = Span(name, "trace", attrs)
        self.error: Optional[str] = None
        # fleet context: the remote parent this trace is a child of, the
        # propagated keep-me sampling decision, and the cross-process id
        # (adopted from the parent, else derived lazily from node+local id)
        self.parent: Optional[RemoteParent] = None
        self.sampled_hint = False
        self._global_id: Optional[str] = None

    @property
    def global_id(self) -> str:
        gid = self._global_id
        if gid is None:
            gid = self._global_id = f"{node_id()}-{self.trace_id}"
        return gid

    @property
    def duration_ms(self) -> float:
        return self.root.duration_ms

    def spans(self) -> Iterator[Span]:
        """Depth-first over every span EXCLUDING the root."""
        for c in self.root.children or ():
            yield from c.walk()

    def kinds(self) -> set:
        return {s.kind for s in self.spans()}

    def self_times_ms(self) -> Dict[str, float]:
        """Total self-time per span kind — the per-stage breakdown."""
        out: Dict[str, float] = {}
        for s in self.spans():
            out[s.kind] = out.get(s.kind, 0.0) + s.self_ms
        return out

    def coverage(self) -> float:
        """Fraction of the root wall time attributed to (non-root) span
        self-times — 1.0 means every microsecond is accounted for."""
        if self.root.duration_ms <= 0:
            return 1.0
        return sum(s.self_ms for s in self.spans()) / self.root.duration_ms

    def to_dict(self) -> dict:
        out = {"id": self.trace_id, "name": self.name, "ts_ms": self.ts_ms,
               "global_id": self.global_id,
               "node": node_id(), "role": _Node.role,
               "duration_ms": round(self.duration_ms, 3),
               "stages_ms": {k: round(v, 3)
                             for k, v in self.self_times_ms().items()},
               "root": self.root.to_dict()}
        try:
            from geomesa_tpu.cluster.runtime import event_dims
            out.update(event_dims())   # process/shard on an active cluster
        except Exception:
            pass
        if self.parent is not None:
            out["parent"] = self.parent.to_dict()
        if self.error is not None:
            out["error"] = self.error
        return out


class TraceRing:
    """Bounded process-global buffer of completed traces (the audit ring;
    ≙ the reference's in-memory audit trail the `_queries` surface reads)."""

    def __init__(self, keep: int = 256):
        self._ring: deque = deque(maxlen=keep)

    def append(self, t: QueryTrace) -> None:
        # lockless: deque appends are GIL-atomic, and this sits on the
        # trace-close hot path; readers retry the mutated-mid-copy race
        self._ring.append(t)

    def recent(self, limit: Optional[int] = None) -> List[dict]:
        """Most-recent-first trace dicts, bounded by ``limit``."""
        while True:
            try:
                items = list(self._ring)
                break
            except RuntimeError:  # mutated during the copy — retry
                continue
        items.reverse()
        if limit is not None:
            items = items[: max(0, int(limit))]
        return [t.to_dict() for t in items]

    def clear(self) -> None:
        self._ring.clear()

    def __len__(self) -> int:
        return len(self._ring)


RING = TraceRing()

# -- observability hooks (obs/ installs these; trace.py stays import-light) --
#
# Close hooks fire once per ROOT trace at close (tail sampling + flight-
# recorder derivation, obs/flight.py / obs/sampling.py); the device hook
# fires per device_fetch with (dispatch_s, wait_s) so per-kernel attribution
# (obs/attrib.py) can charge device time to the kernel an ambient label
# names. Both are None/empty by default — the hot path pays one read.

_close_hooks: List = []
_device_hook = None


def add_close_hook(fn) -> None:
    """Register ``fn(QueryTrace)`` to run at every root-trace close (after
    the trace landed in RING). A raising hook is dropped from that close,
    never the query. Idempotent per function object."""
    if fn not in _close_hooks:
        _close_hooks.append(fn)


def remove_close_hook(fn) -> None:
    if fn in _close_hooks:
        _close_hooks.remove(fn)


def set_device_hook(fn) -> None:
    """Install ``fn(dispatch_s, wait_s)`` called from ``device_fetch`` —
    the per-kernel device-cost attribution slot. None uninstalls."""
    global _device_hook
    _device_hook = fn


def current_trace() -> Optional[QueryTrace]:
    return _local.trace


class span:
    """Context manager timing one stage. Attaches to the active trace (when
    one exists) and feeds the metrics registry under ``name`` either way —
    the drop-in replacement for ``REGISTRY.time(name)``. ~µs overhead when
    enabled; a no-op under ``disabled()``."""

    __slots__ = ("name", "kind", "attrs", "_node", "_t0")

    def __init__(self, name: str, kind: Optional[str] = None, **attrs):
        self.name = name
        self.kind = kind
        self.attrs = attrs or None

    def __enter__(self):
        if not _state.enabled:
            self._t0 = None
            return self
        tr = _local.trace
        if tr is not None:
            node = Span(self.name, self.kind, self.attrs)
            stack = _local.stack
            stack[-1].add_child(node)
            stack.append(node)
            self._node = node
        else:
            self._node = None
        self._t0 = _pc()
        return self

    def __exit__(self, *exc):
        if self._t0 is None:
            return False
        dt = _pc() - self._t0
        node = self._node
        if node is not None:
            # under an active trace the registry feed is DEFERRED to trace
            # close (one batched lock acquisition for the whole span tree),
            # keeping per-span exit cost to pure bookkeeping
            node.duration_ms = dt * 1000
            _local.stack.pop()
        else:
            _REGISTRY.observe(self.name, dt)
        return False


def enabled() -> bool:
    return _state.enabled


def _leaf(name: str, kind: str, duration_ms: float) -> Span:
    """Allocate a completed leaf span without the __init__ frame (hot path)."""
    s = Span.__new__(Span)
    s.name = name
    s.kind = kind
    s.attrs = None
    s.duration_ms = duration_ms
    s.children = None
    s.span_id = None
    return s


def record(name: str, kind: str, seconds: float) -> None:
    """Record an already-timed LEAF stage (no children) without context
    manager dispatch — the minimal-overhead hook for µs-scale hot paths.
    Callers gate their own timing on ``enabled()``."""
    tr = _local.trace
    if tr is not None:
        _local.stack[-1].add_child(_leaf(name, kind, seconds * 1000))
    else:
        _REGISTRY.observe(name, seconds)


def device_fetch(block, dispatch, *args):
    """Fused device_scan + device_wait recorder for the kernel hot path:
    ``block(dispatch(*args))`` with both stages timed through ONE function
    call instead of two context managers (the per-query span overhead budget
    is single-digit µs — see tests/test_perf_budget.py)."""
    if not _state.enabled:
        return block(dispatch(*args))
    t0 = _pc()
    out = dispatch(*args)
    t1 = _pc()
    out = block(out)
    t2 = _pc()
    hook = _device_hook
    if hook is not None:
        hook(t1 - t0, t2 - t1)
    tr = _local.trace
    if tr is not None:
        parent = _local.stack[-1]
        parent.add_child(_leaf("device_scan", "device_scan",
                               (t1 - t0) * 1000))
        parent.add_child(_leaf("device_wait", "device_wait",
                               (t2 - t1) * 1000))
    else:
        _REGISTRY.observe_batch(
            [("device_scan", t1 - t0), ("device_wait", t2 - t1)])
    return out


class trace:
    """Root context manager: opens a QueryTrace, lands it in ``RING`` on
    exit, and feeds the registry timer under ``name``. Re-entrant: under an
    already-active trace it degrades to a nested span (so a datastore-level
    root composes with planner-level instrumentation). Yields the QueryTrace
    (root) or Span (nested) — both expose ``to_dict()`` — or None when
    tracing is disabled."""

    __slots__ = ("name", "attrs", "_t0", "_trace", "_span")

    def __init__(self, name: str, **attrs):
        self.name = name
        self.attrs = attrs or None

    def __enter__(self):
        self._trace = self._span = None
        if not _state.enabled:
            self._t0 = None
            return None
        if _local.trace is not None:
            self._span = span(self.name, kind="trace",
                              **(self.attrs or {}))
            return self._span.__enter__()._node
        t = QueryTrace(self.name, self.attrs)
        remote = _local.remote
        if remote is not None:
            # this root is the remote parent's child: adopt its global id
            # (ONE cross-process trace) and its sampling decision, and
            # consume the context so nested/subsequent roots on this
            # thread don't re-parent under it
            t.parent = remote
            t._global_id = remote.trace_id
            t.sampled_hint = remote.sampled
            _local.remote = None
        _local.trace = t
        _local.stack = [t.root]
        self._trace = t
        self._t0 = _pc()
        return t

    def __exit__(self, *exc):
        if self._span is not None:
            return self._span.__exit__(*exc)
        if self._t0 is None:
            return False
        dt = _pc() - self._t0
        t = self._trace
        t.root.duration_ms = dt * 1000
        if exc and exc[0] is not None:
            t.error = exc[0].__name__
        _local.trace = None
        _local.stack = None
        RING.append(t)
        # deferred feed: the whole span tree drains into the histograms at
        # the next snapshot — trace close pays one list append. The trace id
        # rides along so retained traces become bucket exemplars at drain.
        _REGISTRY.feed_tree(t.root, trace_id=t.trace_id)
        for hook in _close_hooks:
            try:
                hook(t)
            except Exception:
                pass  # observability must never fail the query
        return False
