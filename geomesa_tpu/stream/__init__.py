"""Streaming layer: live feature cache + lambda hot/cold tiering.

≙ reference `geomesa-kafka` + `geomesa-lambda` (SURVEY.md §2.6, §3.6).
"""

from geomesa_tpu.stream.live import GeoMessage, LambdaDataStore, LiveLayer

__all__ = ["GeoMessage", "LambdaDataStore", "LiveLayer"]
