"""Live (streaming) layer: near-real-time features over the indexed store.

≙ reference Kafka tier (SURVEY.md §2.6/§3.6 — KafkaDataStore.scala:55-95,
index/KafkaFeatureCache.scala:25, GeoMessageSerializer.scala) and the Lambda
architecture (lambda/LambdaDataStore.scala — hot Kafka tier + cold persistent
tier merged, DataStorePersistence flushing expired state).

TPU-native shape: the message log is an append-only list of GeoMessages
(CreateOrUpdate / Delete / Clear); the HOT tier materializes surviving
messages into a small columnar table with a full-scan planner (the in-memory
BucketIndex slot); `persist()` moves hot rows into the COLD TpuDataStore
whose sorted device indexes serve the heavy scans — the LSM discipline of
SURVEY.md §7 (delta buffer + periodic merge). Hot rows shadow cold rows by
feature id, exactly like the Lambda tier's union-minus-overlap."""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Union

import numpy as np

from geomesa_tpu.features.table import FeatureTable
from geomesa_tpu.filter import ir
from geomesa_tpu.filter.evaluate import evaluate as _evaluate
from geomesa_tpu.filter.parser import parse_ecql
# -- GeoMessage (≙ kafka/utils/GeoMessage: CreateOrUpdate | Delete | Clear) --


@dataclass
class GeoMessage:
    kind: str                       # "upsert" | "delete" | "clear"
    fid: Optional[str] = None
    attributes: Optional[dict] = None
    ts_ms: int = 0

    @staticmethod
    def upsert(fid: str, attributes: dict, ts_ms: Optional[int] = None) -> "GeoMessage":
        return GeoMessage("upsert", fid, attributes,
                          int(time.time() * 1000) if ts_ms is None else ts_ms)

    @staticmethod
    def delete(fid: str) -> "GeoMessage":
        return GeoMessage("delete", fid, None, int(time.time() * 1000))

    @staticmethod
    def clear() -> "GeoMessage":
        return GeoMessage("clear", None, None, int(time.time() * 1000))


class LiveLayer:
    """In-memory live feature cache (≙ KafkaFeatureCache: latest state per
    fid, optional event-time expiry)."""

    def __init__(self, sft, expiry_ms: Optional[int] = None,
                 event_time: Optional[str] = None):
        self.sft = sft
        self.expiry_ms = expiry_ms
        # expiry clock: an attribute (event time, reference's event-time
        # ordering) or message ingest time
        self.event_time = event_time
        self._state: Dict[str, GeoMessage] = {}   # latest upsert per fid
        self._dirty = True
        self._table: Optional[FeatureTable] = None

    # -- message application (the consumer side of §3.6) ---------------------

    def apply(self, msg: GeoMessage) -> None:
        if msg.kind == "clear":
            self._state.clear()
        elif msg.kind == "delete":
            self._state.pop(msg.fid, None)
        else:
            self._state[msg.fid] = msg
        self._dirty = True

    def put(self, fid: str, ts_ms: Optional[int] = None, **attributes) -> None:
        self.apply(GeoMessage.upsert(fid, attributes, ts_ms))

    def delete(self, fid: str) -> None:
        self.apply(GeoMessage.delete(fid))

    def clear(self) -> None:
        self.apply(GeoMessage.clear())

    # -- expiry --------------------------------------------------------------

    def expire(self, now_ms: Optional[int] = None) -> int:
        """Drop state older than expiry_ms (≙ FeatureStateFactory expiry).
        Returns the number expired."""
        if self.expiry_ms is None:
            return 0
        now = int(time.time() * 1000) if now_ms is None else now_ms
        cutoff = now - self.expiry_ms
        if self.event_time is not None:
            def ts(m):
                v = m.attributes[self.event_time]
                return int(np.datetime64(v, "ms").astype(np.int64)) \
                    if not isinstance(v, (int, np.integer)) else int(v)
        else:
            def ts(m):
                return m.ts_ms
        dead = [fid for fid, m in self._state.items() if ts(m) < cutoff]
        for fid in dead:
            del self._state[fid]
        if dead:
            self._dirty = True
        return len(dead)

    # -- materialized view ---------------------------------------------------

    def __len__(self) -> int:
        return len(self._state)

    @property
    def fids(self) -> List[str]:
        return list(self._state)

    def table(self) -> Optional[FeatureTable]:
        self._materialize()
        return self._table

    def _materialize(self) -> None:
        if not self._dirty:
            return
        self._dirty = False
        if not self._state:
            self._table = None
            return
        fids = list(self._state)
        data: Dict[str, list] = {a.name: [] for a in self.sft.attributes}
        for fid in fids:
            attrs = self._state[fid].attributes
            for a in self.sft.attributes:
                data[a.name].append(attrs[a.name])
        from geomesa_tpu.features.geometry import GeometryArray
        cols: Dict[str, object] = {
            a.name: (GeometryArray.from_rows(data[a.name]) if a.is_geometry
                     else data[a.name])
            for a in self.sft.attributes
        }
        self._table = FeatureTable.build(self.sft, cols, fids=fids)

    # -- queries (served entirely from memory, §3.6) -------------------------

    def query(self, f: Union[str, ir.Filter] = "INCLUDE") -> FeatureTable:
        self._materialize()
        if self._table is None:
            return FeatureTable.build(self.sft, {a.name: [] for a in self.sft.attributes})
        if isinstance(f, str):
            f = parse_ecql(f)
        mask = _evaluate(f, self._table)
        return self._table.take(np.nonzero(mask)[0])

    def count(self, f: Union[str, ir.Filter] = "INCLUDE") -> int:
        self._materialize()
        if self._table is None:
            return 0
        if isinstance(f, str):
            f = parse_ecql(f)
        if isinstance(f, ir.Include):
            return len(self._table)
        return int(_evaluate(f, self._table).sum())


class LambdaDataStore:
    """Hot live tier + cold indexed tier, merged (≙ LambdaDataStore.scala:
    query = union(cache, store minus overlap); persistence flushes the hot
    tier into the cold store).

    Durability: with a ``journal_dir`` the hot tier is write-ahead journaled
    (every GeoMessage logged before it is applied — the moral slot of the
    reference's Kafka topic as the durable message log), and ``persist()``
    becomes a WAL-fenced two-phase move: ``persist_begin(fids)`` is
    journaled, the rows move to the cold store through the ATOMIC
    ``TpuDataStore.upsert`` (one cold-WAL record, idempotent), the captured
    fids are cleared from the hot tier, and ``persist_commit`` closes the
    fence. ``LambdaDataStore.open`` replays the journal on restart and
    completes any begin-without-commit persist idempotently — a crash
    between cold-append and hot-clear can neither drop nor duplicate rows."""

    def __init__(self, cold_store, type_name: str,
                 expiry_ms: Optional[int] = None,
                 event_time: Optional[str] = None,
                 persist_threshold: int = 100_000,
                 journal_dir: Optional[str] = None):
        self.cold = cold_store
        self.type_name = type_name
        self.sft = cold_store.get_schema(type_name)
        self.live = LiveLayer(self.sft, expiry_ms, event_time)
        self.persist_threshold = persist_threshold
        self.journal = None
        if journal_dir is not None:
            from geomesa_tpu.durability.wal import WriteAheadLog
            self.journal = WriteAheadLog(journal_dir, name="journal")

    @classmethod
    def open(cls, cold_store, type_name: str, journal_dir: str,
             expiry_ms: Optional[int] = None,
             event_time: Optional[str] = None,
             persist_threshold: int = 100_000) -> "LambdaDataStore":
        """Recover a journaled hot tier: replay GeoMessages (torn tail
        stops at the first bad CRC), drop fids covered by committed
        persists, and idempotently complete a begin-without-commit persist
        against the (separately recovered) cold store."""
        from geomesa_tpu.durability import wal as _wal
        from geomesa_tpu.durability.wal import WriteAheadLog
        lam = cls(cold_store, type_name, expiry_ms, event_time,
                  persist_threshold)
        last_seq = 0
        pending: Optional[List[str]] = None
        for seq, kind, payload in _wal.iter_records(journal_dir,
                                                    name="journal"):
            last_seq = seq
            meta = _wal.decode_json(payload)
            if kind == "hot_put":
                lam.live.apply(GeoMessage("upsert", meta["fid"],
                                          meta["attributes"],
                                          int(meta["ts_ms"])))
            elif kind == "hot_delete":
                lam.live.apply(GeoMessage("delete", meta["fid"]))
            elif kind == "hot_clear":
                lam.live.apply(GeoMessage.clear())
            elif kind == "hot_expire":
                lam.live.expire(now_ms=int(meta["now_ms"]))
            elif kind == "persist_begin":
                pending = list(meta["fids"])
            elif kind == "persist_commit":
                lam._drop_hot(pending or [])
                pending = None
        lam.journal = WriteAheadLog(journal_dir, name="journal",
                                    start_seq=last_seq + 1)
        if pending is not None:
            lam._complete_persist(pending)
        return lam

    def close(self) -> None:
        if self.journal is not None:
            self.journal.close()

    # -- writes land in the hot tier -----------------------------------------

    def put(self, fid: str, **attributes) -> None:
        msg = GeoMessage.upsert(fid, attributes)
        if self.journal is not None:
            self.journal.append_json("hot_put", {
                "fid": fid, "attributes": attributes, "ts_ms": msg.ts_ms})
        self.live.apply(msg)
        if len(self.live) >= self.persist_threshold:
            self.persist()

    def delete(self, fid: str) -> None:
        """Remove from the hot tier AND the cold tier — a delete must reach
        whichever tier currently holds the feature (≙ the lambda tier
        writing Kafka deletes while also deleting from the persistent store)."""
        if self.journal is not None:
            self.journal.append_json("hot_delete", {"fid": fid})
        self.live.delete(fid)
        if self.cold.tables.get(self.type_name) is not None:
            self.cold.remove_features(self.type_name, ir.FidFilter((fid,)))

    def expire(self, now_ms: Optional[int] = None) -> int:
        """Journaled event/ingest-time expiry of the hot tier (the clock is
        resolved before logging so replay uses the same cutoff)."""
        now = int(time.time() * 1000) if now_ms is None else int(now_ms)
        if self.journal is not None:
            self.journal.append_json("hot_expire", {"now_ms": now})
        return self.live.expire(now)

    def _drop_hot(self, fids) -> None:
        """Remove exactly these fids from the hot tier (not a blanket
        clear: puts that raced in after the persist captured its table
        survive)."""
        dropped = False
        for fid in fids:
            if self.live._state.pop(fid, None) is not None:
                dropped = True
        if dropped:
            self.live._dirty = True

    def persist(self) -> int:
        """Move the hot tier into the cold store (≙ DataStorePersistence).
        Hot rows that shadow cold fids replace them. The move itself is the
        cold store's atomic ``upsert`` (remove-duplicates + append under one
        lock hold, one WAL record) — re-running it after a crash at ANY
        point converges instead of losing or double-counting rows, because
        until the hot fids are dropped they shadow their cold copies on
        every read. BECAUSE the upsert is idempotent it is also safe to
        retry, so transient cold-store failures (a WAL fsync hiccup under
        'always') ride the shared capped-backoff retry wrapper instead of
        stranding rows in the hot tier. Returns rows flushed."""
        table = self.live.table()
        if table is None:
            return 0
        fids = [str(f) for f in table.fids]
        if self.journal is not None:
            self.journal.append_json("persist_begin", {"fids": fids})
        from geomesa_tpu.serve.resilience.breaker import retry_call
        retry_call(lambda: self.cold.upsert(self.type_name, table),
                   counter="stream.persist_retries")
        self._drop_hot(fids)
        if self.journal is not None:
            self.journal.append_json("persist_commit", {"n": len(fids)})
        return len(table)

    def _complete_persist(self, fids) -> int:
        """Finish a begin-without-commit persist found at recovery: re-move
        whichever of its fids still sit in the hot tier (idempotent against
        a cold store that already replayed the original upsert) and close
        the fence."""
        present = [f for f in fids if f in self.live._state]
        if present:
            table = self.live.table()
            idx = np.flatnonzero(np.isin(
                np.asarray(table.fids, dtype=object),
                np.asarray(present, dtype=object)))
            self.cold.upsert(self.type_name, table.take(idx))
            self._drop_hot(present)
        self.journal.append_json("persist_commit",
                                 {"n": len(present), "recovered": True})
        return len(present)

    # -- merged reads --------------------------------------------------------

    def count(self, f: Union[str, ir.Filter] = "INCLUDE",
              deadline_ms: Optional[float] = None) -> int:
        """Merged hot+cold count; ``deadline_ms`` installs a per-request
        deadline that the cold planner's checkpoints honor."""
        from geomesa_tpu.serve.resilience import deadline as _rdl
        with _rdl.scope(deadline_ms):
            return len(self.query_indices(f)[0]) + self.live.count(f)

    def query(self, f: Union[str, ir.Filter] = "INCLUDE",
              deadline_ms: Optional[float] = None) -> FeatureTable:
        from geomesa_tpu.serve.resilience import deadline as _rdl
        with _rdl.scope(deadline_ms):
            return self._query_impl(f)

    def _query_impl(self, f) -> FeatureTable:
        rows, planner = self.query_indices(f)
        cold_part = planner.table.take(rows) if planner is not None else None
        hot_part = self.live.query(f)
        if cold_part is None or len(cold_part) == 0:
            return hot_part
        if len(hot_part) == 0:
            return cold_part
        return FeatureTable.concat([cold_part, hot_part])

    def query_indices(self, f):
        """Cold-tier row indices minus rows shadowed by hot fids."""
        if self.cold.tables.get(self.type_name) is None:
            return np.empty(0, dtype=np.int64), None
        planner = self.cold.planner(self.type_name)
        rows = planner.select_indices(f)
        hot = self.live.fids
        if hot and len(rows):
            rows = rows[~np.isin(planner.table.fids[rows],
                                 np.asarray(hot, dtype=object))]
        return rows, planner
